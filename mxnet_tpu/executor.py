"""Executor — binds a Symbol to devices and runs it.

Reference: src/executor/graph_executor.cc (GraphExecutor::Init :512/:951,
Forward :81, Backward :94, RunOps :1469) + python/mxnet/executor.py.

TPU-native design: where the reference turns each graph node into one engine
op (InitCachedOps, graph_executor.cc:1221) and bulk-fuses segments
(InitOpSegs :1340), here the ENTIRE graph lowers to one pure JAX function —
forward is one jitted XLA computation, forward+backward another.  The nnvm
passes map as: Gradient → jax.vjp; InferShape → jax.eval_shape + param
hints; PlanMemory/DetectInplaceAddTo → XLA buffer assignment + donation;
PlaceDevice/ctx_group → sharding annotations (see parallel/).
"""
from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, dtype_np, dtype_name
from .context import Context, cpu
from .ndarray.ndarray import NDArray, zeros as nd_zeros, array as nd_array
from .ops import shape_hints  # installs infer_params hooks  # noqa: F401
from .symbol.symbol import Node, NodeEntry, Symbol, _topo_order
from . import rng as _rng

__all__ = ["Executor", "GraphProgram", "infer_shapes", "infer_types",
           "set_backward_mirror", "backward_mirror_policy",
           "apply_backward_mirror"]


# ---------------------------------------------------------------------------
# Activation-memory mirroring (MXNET_BACKWARD_DO_MIRROR analog).
#
# The reference recomputes cheap forward nodes during backward instead of
# keeping their activations (src/executor/graph_executor.cc:253-311,
# docs/faq/env_var.md:89-94), trading ~30-50% activation memory for ~5% step
# time.  TPU-native analog: jax.checkpoint (remat) around the whole forward,
# with an XLA rematerialisation policy choosing what to keep:
#
#   'none'          - keep every activation (no remat)
#   'dots'          - keep matmul/conv outputs, recompute elementwise/norm
#                     chains (closest to the reference mirror heuristic)
#   'dots_no_batch' - keep only weight-style matmuls (no batch dims)
#   'full'          - keep nothing; recompute the entire forward in backward
#
# Selection: set_backward_mirror(policy) > MXNET_TPU_REMAT_POLICY >
# MXNET_BACKWARD_DO_MIRROR=1 (maps to 'dots').
# ---------------------------------------------------------------------------

_mirror_override: Optional[str] = None


def set_backward_mirror(policy: Optional[str]):
    """Select the activation-remat policy programmatically.

    policy: 'none' | 'dots' | 'dots_no_batch' | 'full' | None (None defers
    back to the MXNET_TPU_REMAT_POLICY / MXNET_BACKWARD_DO_MIRROR env vars).
    """
    global _mirror_override
    if policy is not None and policy not in _REMAT_POLICIES:
        raise ValueError("unknown remat policy %r (choose from %s)"
                         % (policy, sorted(_REMAT_POLICIES)))
    _mirror_override = policy


def backward_mirror_policy() -> str:
    """Resolve the active remat policy name."""
    import os
    if _mirror_override is not None:
        return _mirror_override
    env = os.environ.get("MXNET_TPU_REMAT_POLICY")
    if env:
        if env not in _REMAT_POLICIES:
            import warnings
            warnings.warn("MXNET_TPU_REMAT_POLICY=%r is not one of %s; "
                          "remat stays off" % (env, sorted(_REMAT_POLICIES)))
            return "none"
        return env
    if os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0") not in ("0", ""):
        return "dots"
    return "none"


def apply_backward_mirror(fn, policy: Optional[str] = None):
    """Public remat helper for raw-JAX training loops: wrap a pure forward
    (or loss) function so its activations are rematerialized during
    backward per `policy` (None = the currently active policy; see
    set_backward_mirror)."""
    return _remat_wrap(fn, policy if policy is not None
                       else backward_mirror_policy())


def _remat_wrap(fn, policy: str):
    """Wrap a pure forward fn in jax.checkpoint per the named policy."""
    if policy == "none":
        return fn
    xla_policy = _REMAT_POLICIES[policy]()
    if xla_policy is None:   # 'full': keep nothing (jax.checkpoint default)
        return jax.checkpoint(fn)
    return jax.checkpoint(fn, policy=xla_policy)


_REMAT_POLICIES = {
    "none": lambda: None,
    "full": lambda: None,
    "dots": lambda: jax.checkpoint_policies.dots_saveable,
    "dots_no_batch":
        lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def batch_hint_from(arg_map: Dict[str, Any], arg_names: Sequence[str]):
    """Leading-dim hint used to resolve 0-dims in creation-op shapes (the
    reference begin_state convention): the 'data' arg if present, else the
    first argument that has a shape."""
    if "data" in arg_map and hasattr(arg_map["data"], "shape"):
        return arg_map["data"].shape[0]
    for n in arg_names:
        v = arg_map.get(n)
        if hasattr(v, "shape") and v.shape:
            return v.shape[0]
    return None


def node_attrs(node, train: bool, batch_hint):
    """Attrs for evaluating one graph node: 0-dims resolved against the
    batch hint, _train injected for mode-dependent ops.  Single source of
    truth for GraphProgram.evaluate and placement.SegmentedProgram."""
    attrs = node.parsed_attrs()
    if not node.inputs and 0 in (attrs.get("shape") or ()):
        if not batch_hint:
            raise ValueError(
                "creation op %r has 0-dim shape %r but no batch hint is "
                "available to resolve it (bind with a 'data' input or a "
                "shaped argument)" % (node.op.name, attrs.get("shape")))
        attrs = type(attrs)(attrs)
        attrs["shape"] = tuple(batch_hint if d == 0 else d
                               for d in attrs["shape"])
    if node.op.mode_dependent:
        attrs = type(attrs)(attrs)
        attrs["_train"] = train
    return attrs


def _shard_constrain_outputs(out, ann, name):
    """Activation placement: a ``__shard__`` attr on an *op* node pins
    the op's outputs to a mesh spec via ``with_sharding_constraint``, so
    GSPMD anchors its propagation there instead of guessing (the
    placement-layer analog of the reference's per-node ctx_group).  The
    annotation grammar and the resolution both live in
    parallel/placement.py — one grammar for params AND activations.
    Inert (identity) unless a mesh is active (parallel.mesh
    .set_current_mesh — ShardedTrainer arms it around its traces), so
    single-device paths never pay for it."""
    from .placement import activation_constraint
    return activation_constraint(out, ann, name)


class GraphProgram:
    """A Symbol compiled into a pure function.

    fn(arg_arrays, aux_arrays, keys, train) evaluates the whole DAG.
    Shared by Executor, CachedOp (gluon) and Module's fused train step.
    """

    def __init__(self, symbol: Symbol):
        self.symbol = symbol
        self.nodes = _topo_order(symbol._entries)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        aux_ids = symbol._aux_var_ids()
        self.var_kind: Dict[int, str] = {}
        for n in self.nodes:
            if n.is_var:
                self.var_kind[id(n)] = "aux" if id(n) in aux_ids else "arg"
        # rng nodes, in topo order
        self.rng_nodes = [n for n in self.nodes
                          if not n.is_var and n.op.needs_rng]
        self.num_rng = len(self.rng_nodes)
        # aux writeback plan: list of (aux_name, node, out_idx)
        self.aux_updates = []
        for n in self.nodes:
            if n.is_var or not n.op.writeback:
                continue
            for i_in, i_out in n.op.writeback_map(n.parsed_attrs()).items():
                if i_in < len(n.inputs):
                    src = n.inputs[i_in].node
                    if src.is_var and id(src) in aux_ids:
                        self.aux_updates.append((src.name, n, i_out))

    def evaluate(self, arg_arrays: Sequence, aux_arrays: Sequence,
                 keys, train: bool):
        """Pure evaluation. Returns (outputs, new_aux)."""
        outputs, new_aux, _ = self._evaluate_impl(
            arg_arrays, aux_arrays, keys, train, tap=False)
        return outputs, new_aux

    def tap_names(self):
        """Names of every non-variable node output, in topo order — the
        per-node tap points the reference monitor sees
        (graph_executor.cc:121 invokes the callback on every op output)."""
        names = []
        for node in self.nodes:
            if node.is_var:
                continue
            n_vis = node.op.num_visible_outputs(node.parsed_attrs())
            if n_vis == 1:
                names.append(node.name + "_output")
            else:
                # multi-output nodes number every output, matching
                # Symbol.list_outputs ("<name>_output0", "<name>_output1", …)
                names.extend(node.name + "_output%d" % i
                             for i in range(n_vis))
        return names

    def _evaluate_impl(self, arg_arrays, aux_arrays, keys, train: bool,
                       tap: bool):
        arg_map = dict(zip(self.arg_names, arg_arrays))
        aux_map = dict(zip(self.aux_names, aux_arrays))
        batch_hint = batch_hint_from(arg_map, self.arg_names)
        key_idx = 0
        raw: Dict[int, tuple] = {}
        taps = []
        for node in self.nodes:
            if node.is_var:
                kind = self.var_kind[id(node)]
                val = arg_map[node.name] if kind == "arg" else aux_map[node.name]
                raw[id(node)] = (val,)
                continue
            attrs = node_attrs(node, train, batch_hint)
            ins = [raw[id(e.node)][e.index] for e in node.inputs]
            if node.op.needs_rng:
                ins = [keys[key_idx]] + ins
                key_idx += 1
            out = node.op.fn(attrs, *ins)
            out = out if isinstance(out, tuple) else (out,)
            ann = node.attrs.get("__shard__") if node.attrs else None
            if ann is not None:
                out = _shard_constrain_outputs(out, ann, node.name)
            raw[id(node)] = out
            if tap:
                taps.extend(out[:node.op.num_visible_outputs(attrs)])
        outputs = [raw[id(e.node)][e.index] for e in self.symbol._entries]
        new_aux = list(aux_arrays)
        aux_pos = {n: i for i, n in enumerate(self.aux_names)}
        for aux_name, node, i_out in self.aux_updates:
            new_aux[aux_pos[aux_name]] = raw[id(node)][i_out]
        return tuple(outputs), tuple(new_aux), tuple(taps)

    # jitted entry points -------------------------------------------------
    @functools.lru_cache(maxsize=None)
    def _jit_forward(self, train: bool):
        def f(args, aux, keys):
            return self.evaluate(args, aux, keys, train)
        return jax.jit(f)

    @functools.lru_cache(maxsize=None)
    def _jit_forward_tapped(self, train: bool):
        """Forward that also returns every node output (monitor support)."""
        def f(args, aux, keys):
            return self._evaluate_impl(args, aux, keys, train, tap=True)
        return jax.jit(f)

    def _jit_fwd_bwd(self, train: bool, grad_mask: tuple):
        """One XLA computation: outputs + grads of selected args + new aux."""
        return self._jit_fwd_bwd_impl(train, grad_mask,
                                      backward_mirror_policy())

    @functools.lru_cache(maxsize=None)
    def _jit_fwd_bwd_impl(self, train: bool, grad_mask: tuple, remat: str):
        def f(args, aux, keys, out_cots):
            diff_args = [a for a, m in zip(args, grad_mask) if m]

            def split_fn(diff):
                it = iter(diff)
                full = [next(it) if m else a for a, m in zip(args, grad_mask)]
                outs, new_aux = self.evaluate(full, aux, keys, train)
                return outs, new_aux

            (outs, new_aux), vjp = jax.vjp(_remat_wrap(split_fn, remat),
                                           diff_args)
            zero_aux = tuple(jnp.zeros_like(a) for a in new_aux)
            (grads,) = vjp((tuple(out_cots), zero_aux))
            return outs, new_aux, grads
        return jax.jit(f)


def _struct(shape, dtype="float32"):
    return jax.ShapeDtypeStruct(tuple(shape), dtype_np(dtype))


def _resolve_structs(symbol: Symbol, kwargs: Dict[str, Any],
                     type_dict=None, partial=False):
    """Bidirectional-ish shape inference: walk the graph forward, filling
    unknown parameter shapes via infer_params hooks (shape_hints.py), then
    output shapes via jax.eval_shape per node."""
    prog = GraphProgram(symbol)
    type_dict = type_dict or {}
    known: Dict[str, jax.ShapeDtypeStruct] = {}
    for k, v in (kwargs or {}).items():
        if v is None:
            continue
        if isinstance(v, jax.ShapeDtypeStruct):
            known[k] = v
        elif isinstance(v, (tuple, list)):
            known[k] = _struct(v, type_dict.get(k, "float32"))
        elif isinstance(v, NDArray):
            known[k] = _struct(v.shape, v.dtype)
    batch_hint = None
    for cand in ("data", "data0"):
        if cand in known:
            batch_hint = known[cand].shape[0] if known[cand].shape else None
            break
    if batch_hint is None and known:
        first = next(iter(known.values()))
        batch_hint = first.shape[0] if first.shape else None
    shapes: Dict[int, tuple] = {}  # node id -> tuple of output structs
    for node in prog.nodes:
        if node.is_var:
            if node.name in known:
                shapes[id(node)] = (known[node.name],)
            elif "__shape__" in node.attrs:
                import ast
                shp = ast.literal_eval(str(node.attrs["__shape__"]))
                if shp is None or any((d is None or d <= 0) for d in shp):
                    shapes[id(node)] = (None,)  # partially-known: infer
                else:
                    dt = type_dict.get(node.name,
                                       node.attrs.get("__dtype__", "float32"))
                    s = _struct(shp, dt)
                    known[node.name] = s
                    shapes[id(node)] = (s,)
            else:
                shapes[id(node)] = (None,)
            continue
        # same 0-dim policy as evaluation (node_attrs): fail at bind time,
        # not first forward, when a 0-dim cannot be resolved
        try:
            attrs = node_attrs(node, train=False, batch_hint=batch_hint)
        except ValueError:
            if partial:
                shapes[id(node)] = (None,) * node.num_outputs()
                continue
            raise
        in_structs = [shapes[id(e.node)][e.index] for e in node.inputs]
        hook = getattr(node.op, "infer_params", None)
        if hook is not None and any(s is None for s in in_structs):
            in_shapes = [tuple(s.shape) if s is not None else None
                         for s in in_structs]
            try:
                hints = hook(attrs, in_shapes)
            except Exception:
                hints = {}
            for idx, shp in hints.items():
                if idx < len(in_structs) and in_structs[idx] is None:
                    var_node = node.inputs[idx].node
                    dt = type_dict.get(var_node.name, None)
                    if dt is None:
                        dt = in_structs[0].dtype if in_structs[0] is not None \
                            else "float32"
                    s = _struct(shp, dt)
                    in_structs[idx] = s
                    if var_node.is_var:
                        known[var_node.name] = s
                        shapes[id(var_node)] = (s,)
        if any(s is None for s in in_structs):
            if partial:
                shapes[id(node)] = (None,) * node.num_outputs()
                continue
            missing = [node.inputs[i].node.name
                       for i, s in enumerate(in_structs) if s is None]
            raise MXNetError(
                "infer_shape: cannot determine shape of %s (inputs of node "
                "%s); provide it explicitly" % (missing, node.name))
        a2 = attrs
        if node.op.mode_dependent:
            a2 = type(attrs)(attrs)
            a2["_train"] = False
        ins = list(in_structs)
        if node.op.needs_rng:
            ins = [jax.ShapeDtypeStruct((2,), np.uint32)] + ins
        out = jax.eval_shape(functools.partial(node.op.fn, a2), *ins)
        shapes[id(node)] = tuple(out) if isinstance(out, (tuple, list)) \
            else (out,)
    return prog, known, shapes


def infer_shapes(symbol: Symbol, kwargs, partial=False):
    prog, known, shapes = _resolve_structs(symbol, kwargs, partial=partial)
    arg_shapes = [tuple(known[n].shape) if n in known else None
                  for n in prog.arg_names]
    out_shapes = []
    for e in symbol._entries:
        s = shapes[id(e.node)][e.index]
        out_shapes.append(tuple(s.shape) if s is not None else None)
    aux_shapes = [tuple(known[n].shape) if n in known else None
                  for n in prog.aux_names]
    return arg_shapes, out_shapes, aux_shapes


# ops whose output dtype follows a specific (non-first) input: lookup ops
# emit the dtype of their table, not of their integer indices
_DTYPE_FOLLOWS_INPUT = {"Embedding": 1, "take": 0, "gather_nd": 0}

# inputs pinned to a fixed dtype regardless of the data dtype: BatchNorm
# keeps gamma/beta and the moving stats float32 under fp16/bf16 data
# (reference batch_norm.cc type inference)
_DTYPE_PINNED_INPUTS = {"BatchNorm": {1: "float32", 2: "float32",
                                      3: "float32", 4: "float32"}}


def infer_types(symbol: Symbol, kwargs):
    """Type inference given arg dtypes (reference Symbol.infer_type,
    src/executor/infer_graph_attr_pass.cc).

    Forward dtype propagation through the graph: a node's output dtype is
    its declared ``dtype`` attr (Cast, creation ops) if present, else the
    dtype of the input it follows (first input for most ops — the
    reference's same-type constraint — with a small table for lookup ops
    like Embedding whose output follows the table, not the indices).
    Unknown variables encountered as other inputs of the node adopt that
    same dtype (params follow data), matching the reference's propagation
    of the data type into weights."""
    prog = GraphProgram(symbol)
    type_dict = {k: dtype_name(v) for k, v in (kwargs or {}).items()
                 if v is not None}   # None = "unknown, please infer"
    default_dt = next(iter(type_dict.values()), "float32")
    dts: Dict[int, tuple] = {}   # node id -> per-output dtype names
    for node in prog.nodes:
        if node.is_var:
            d = type_dict.get(node.name) or node.attrs.get("__dtype__")
            dts[id(node)] = (dtype_name(d) if d else None,)
            continue
        attrs = node.parsed_attrs()
        # only a USER-set dtype attr declares the output dtype — parsed
        # attrs fill schema defaults (topk/argsort carry dtype='float32'
        # by default while their runtime output follows the input)
        declared = node.attrs.get("dtype")
        in_dts = [dts[id(e.node)][e.index] for e in node.inputs]
        if not node.inputs:
            # creation op: its (possibly default) dtype param IS the output
            anchor = dtype_name(attrs.get("dtype") or default_dt)
        elif node.op.name in _DTYPE_FOLLOWS_INPUT:
            # lookup op: dtype comes from the table input ONLY — integer
            # indices must not donate their dtype to an untyped table;
            # fall back to the op's dtype param (Embedding), never to the
            # index dtype
            f = _DTYPE_FOLLOWS_INPUT[node.op.name]
            anchor = in_dts[f] if f < len(in_dts) and in_dts[f] is not None \
                else dtype_name(attrs.get("dtype") or "float32")
        else:
            anchor = next((d for d in in_dts if d is not None), default_dt)
        # untyped variable inputs adopt the node's anchor dtype (pinned
        # inputs — BN params/stats — keep their fixed dtype instead)
        pinned = _DTYPE_PINNED_INPUTS.get(node.op.name, {})
        for i, (e, d) in enumerate(zip(node.inputs, in_dts)):
            if d is None and e.node.is_var:
                dts[id(e.node)] = (pinned.get(i, anchor),)
        out_dt = dtype_name(declared) if declared else anchor
        dts[id(node)] = (out_dt,) * node.op.num_outputs(attrs)
    def _final(name_nodes):
        return [np.dtype(dtype_np(dts[id(n)][0] or default_dt))
                for n in name_nodes]
    by_name = {n.name: n for n in prog.nodes if n.is_var}
    arg_types = _final([by_name[n] for n in prog.arg_names])
    out_types = [np.dtype(dtype_np(dts[id(e.node)][e.index] or default_dt))
                 for e in symbol._entries]
    aux_types = [np.dtype(dtype_np(dts[id(by_name[n])][0] or "float32"))
                 for n in prog.aux_names]
    return arg_types, out_types, aux_types


def infer_storage_types(symbol: Symbol, kwargs):
    """Storage-type inference (reference Symbol.infer_storage_type over
    FInferStorageType, infer_graph_attr_pass.cc).

    Forward propagation of {'default','row_sparse','csr'} tags through
    the graph.  An op with a registered ``stype_rule`` (ops/
    sparse_storage.py) declares its output storage; every other op is a
    dense producer — sparse inputs densify at its edge, the reference's
    dense-fallback path.  Variables default to 'default' unless given in
    `kwargs` or tagged with a ``__storage_type__`` attr.

    Returns (arg_stypes, out_stypes, aux_stypes) as strings."""
    prog = GraphProgram(symbol)
    given = {k: v for k, v in (kwargs or {}).items() if v}
    sts: Dict[int, tuple] = {}
    for node in prog.nodes:
        if node.is_var:
            st = given.get(node.name) or \
                node.attrs.get("__storage_type__", "default")
            sts[id(node)] = (st,)
            continue
        in_sts = tuple(sts[id(e.node)][e.index] for e in node.inputs)
        rule = getattr(node.op, "stype_rule", None)
        attrs = node.parsed_attrs()
        if rule is not None:
            out = tuple(rule(attrs, in_sts))
            n_out = node.op.num_outputs(attrs)
            if len(out) < n_out:
                out = out + ("default",) * (n_out - len(out))
        else:
            out = ("default",) * node.op.num_outputs(attrs)
        sts[id(node)] = out
    by_name = {n.name: n for n in prog.nodes if n.is_var}
    arg_sts = [sts[id(by_name[n])][0] for n in prog.arg_names]
    out_sts = [sts[id(e.node)][e.index] for e in symbol._entries]
    aux_sts = [sts[id(by_name[n])][0] for n in prog.aux_names]
    return arg_sts, out_sts, aux_sts


class Executor:
    """Bound computation (reference python/mxnet/executor.py).

    forward() → one jitted XLA call; backward()/run_fwd_bwd() → one jitted
    XLA call computing outputs + gradients together.
    """

    def __init__(self, symbol: Symbol, ctx: Context,
                 args, args_grad=None, grad_req="write", aux_states=None,
                 shared_exec: Optional["Executor"] = None, program=None,
                 group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else cpu()
        if program is not None:
            self._prog = program
        elif shared_exec is not None and shared_exec._symbol is symbol:
            self._prog = shared_exec._prog
        else:
            self._prog = GraphProgram(symbol)
        arg_names = self._prog.arg_names

        if isinstance(args, dict):
            self.arg_arrays = [args[n] for n in arg_names]
        else:
            self.arg_arrays = list(args)
        self.arg_dict = dict(zip(arg_names, self.arg_arrays))

        aux_names = self._prog.aux_names
        if aux_states is None:
            aux_states = []
        if isinstance(aux_states, dict):
            self.aux_arrays = [aux_states[n] for n in aux_names]
        else:
            self.aux_arrays = list(aux_states)
        self.aux_dict = dict(zip(aux_names, self.aux_arrays))

        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(arg_names, grad_req))
        else:
            self.grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        if args_grad is None:
            self.grad_arrays = [None] * len(arg_names)
        elif isinstance(args_grad, dict):
            self.grad_arrays = [args_grad.get(n) for n in arg_names]
        else:
            self.grad_arrays = list(args_grad)
            if len(self.grad_arrays) < len(arg_names):
                self.grad_arrays += [None] * (len(arg_names) -
                                              len(self.grad_arrays))
        self.grad_dict = {n: g for n, g in zip(arg_names, self.grad_arrays)}

        self.outputs: List[NDArray] = []
        self._monitor_callback = None
        self._monitor_all = False
        self._last_keys = None  # RNG keys of the last forward, for backward

        # ctx_group model parallelism: if the symbol carries grouped nodes
        # that map to a device other than the bind device, execute via the
        # segmented per-device program (placement.py) instead of one jit.
        self._seg = None
        if group2ctx:
            from .placement import SegmentedProgram, group_devices
            devs = group_devices(symbol, group2ctx)
            if devs and devs != {self._ctx.jax_device}:
                self._seg = SegmentedProgram(self._prog, group2ctx, self._ctx)

    # -- binding helpers -------------------------------------------------
    @staticmethod
    def simple_bind(symbol: Symbol, ctx, grad_req="write", type_dict=None,
                    shared_exec=None, group2ctx=None, **kwargs):
        prog, known, shapes = _resolve_structs(symbol, kwargs, type_dict)
        missing = [n for n in prog.arg_names if n not in known]
        if missing:
            raise MXNetError("simple_bind: could not infer shapes for %s"
                             % missing)
        args = {n: nd_zeros(tuple(known[n].shape),
                            dtype=np.dtype(known[n].dtype), ctx=ctx)
                for n in prog.arg_names}
        aux = {n: nd_zeros(tuple(known[n].shape),
                           dtype=np.dtype(known[n].dtype), ctx=ctx)
               for n in prog.aux_names}
        greq = grad_req if isinstance(grad_req, dict) else \
            {n: grad_req for n in prog.arg_names}
        grads = {n: nd_zeros(tuple(known[n].shape),
                             dtype=np.dtype(known[n].dtype), ctx=ctx)
                 for n in prog.arg_names if greq.get(n, "null") != "null"}
        return Executor(symbol, ctx, args, args_grad=grads, grad_req=greq,
                        aux_states=aux, program=prog, group2ctx=group2ctx)

    # -- execution -------------------------------------------------------
    def _keys(self):
        if self._prog.num_rng == 0:
            return jnp.zeros((0, 2), dtype=jnp.uint32)
        return jnp.stack([_rng.next_key() for _ in range(self._prog.num_rng)])

    def _commit(self, h):
        """Place an incoming array on this executor's device."""
        return jax.device_put(h, self._ctx.jax_device)

    def _seg_grads(self, gmap, mask):
        """Order the segmented-path grad dict per arg_names.  A masked arg
        that received no cotangent (disconnected from the loss) gets zeros,
        matching the _jit_fwd_bwd path, rather than keeping a possibly
        uninitialized grad buffer."""
        grads = []
        out_mask = []
        for n, m in zip(self._prog.arg_names, mask):
            if not m:
                out_mask.append(False)
                continue
            if n in gmap:
                grads.append(gmap[n])
                out_mask.append(True)
            elif self.grad_dict.get(n) is not None:
                tgt = self.grad_dict[n]
                grads.append(jnp.zeros(tuple(tgt.shape),
                                       dtype=np.dtype(tgt.dtype)))
                out_mask.append(True)
            else:
                # masked but no grad buffer to write — drop from the mask
                out_mask.append(False)
        return tuple(grads), tuple(out_mask)

    def _prof_tic(self):
        from . import profiler as _prof
        return time.perf_counter() * 1e6 if _prof.is_running() else None

    def _prof_toc(self, t0, suffix, results):
        """Record one timed executor-step event (true wall time: profile
        mode syncs on the result, matching the reference engine timing)."""
        if t0 is None:
            return
        from . import profiler as _prof
        jax.block_until_ready(results)
        name = (self._symbol.name or "graph") + suffix
        _prof.record_event(name, t0, time.perf_counter() * 1e6 - t0,
                           cat="symbolic")

    def _seg_forward(self, args, aux, keys, is_train):
        """Forward through the segmented (ctx_group) program; aux returned
        in aux_names order."""
        outs, new_aux_map, _ = self._seg.run(
            dict(zip(self._prog.arg_names, args)),
            dict(zip(self._prog.aux_names, aux)),
            keys, bool(is_train))
        return outs, tuple(new_aux_map[n] for n in self._prog.aux_names)

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                tgt = self.arg_dict[k]
                tgt._handle = self._commit(
                    v._handle if isinstance(v, NDArray) else jnp.asarray(v))
        args = tuple(a._handle for a in self.arg_arrays)
        aux = tuple(a._handle for a in self.aux_arrays)
        keys = self._keys()
        if is_train:
            # only a train forward defines the mask backward must reuse; an
            # interleaved eval forward (monitor/validation) must not clobber it
            self._last_keys = keys
        taps = None
        t0 = self._prof_tic()
        if self._seg is not None:
            outs, new_aux = self._seg_forward(args, aux, keys, is_train)
        elif self._monitor_active() and self._monitor_all:
            outs, new_aux, taps = self._prog._jit_forward_tapped(
                bool(is_train))(args, aux, keys)
        else:
            fn = self._prog._jit_forward(bool(is_train))
            outs, new_aux = fn(args, aux, keys)
        self._prof_toc(t0, "_forward", outs)
        if is_train:
            for nd_, na in zip(self.aux_arrays, new_aux):
                nd_._handle = na
        self.outputs = [NDArray(o) for o in outs]
        if self._monitor_active():
            self._fire_monitor(args, aux, keys, is_train, self.outputs,
                               taps=taps)
        return self.outputs

    def _write_grads(self, grads, mask):
        gi = iter(grads)
        for name, m in zip(self._prog.arg_names, mask):
            if not m:
                continue
            g = next(gi)
            tgt = self.grad_dict.get(name)
            if tgt is None:
                continue
            if self._seg is not None:
                # grads come back on their segment's device; the grad buffer
                # (and the optimizer update) live on the bind device
                g = self._commit(g)
            if self.grad_req[name] == "add":
                tgt._handle = tgt._handle + g.astype(tgt._handle.dtype)
            else:
                tgt._handle = g.astype(tgt._handle.dtype)

    def backward(self, out_grads=None, is_train=True):
        mask = tuple(self.grad_req.get(n, "null") != "null"
                     for n in self._prog.arg_names)
        if not any(mask):
            return
        args = tuple(a._handle for a in self.arg_arrays)
        aux = tuple(a._handle for a in self.aux_arrays)
        # Reuse the RNG keys of the preceding forward so dropout masks etc.
        # match between the forward outputs and these gradients (reference
        # reuses forward state); only draw fresh keys with no prior forward.
        keys = self._last_keys if self._last_keys is not None else self._keys()
        if out_grads is None:
            if self.outputs:
                cots = tuple(jnp.ones_like(o._handle) for o in self.outputs)
            else:
                structs = jax.eval_shape(self._prog._jit_forward(bool(is_train)),
                                         args, aux, keys)[0]
                cots = tuple(jnp.ones(s.shape, s.dtype) for s in structs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cots = tuple(g._handle if isinstance(g, NDArray) else jnp.asarray(g)
                         for g in out_grads)
        t0 = self._prof_tic()
        if self._seg is not None:
            gm = dict(zip(self._prog.arg_names, mask))
            _, _, gmap = self._seg.run(dict(zip(self._prog.arg_names, args)),
                                       dict(zip(self._prog.aux_names, aux)),
                                       keys, bool(is_train),
                                       grad_mask=gm, out_cots=cots)
            grads, mask = self._seg_grads(gmap, mask)
        else:
            fn = self._prog._jit_fwd_bwd(bool(is_train), mask)
            _, _, grads = fn(args, aux, keys, cots)
        self._prof_toc(t0, "_backward", grads)
        self._write_grads(grads, mask)

    def run_fwd_bwd(self, out_cots=None, is_train=True):
        """Fused forward+backward: ONE XLA computation (the perf path used
        by Module).  Returns outputs; grads written per grad_req; aux
        updated."""
        mask = tuple(self.grad_req.get(n, "null") != "null"
                     for n in self._prog.arg_names)
        args = tuple(a._handle for a in self.arg_arrays)
        aux = tuple(a._handle for a in self.aux_arrays)
        keys = self._keys()
        self._last_keys = keys
        t0 = self._prof_tic()
        if not any(mask):
            if self._seg is not None:
                # aux handles live on segment devices after a segmented step;
                # the single-device jit would see mixed devices and either
                # fail or silently ignore placement
                outs, new_aux = self._seg_forward(args, aux, keys, is_train)
            else:
                outs, new_aux = self._prog._jit_forward(bool(is_train))(
                    args, aux, keys)
            grads = ()
        elif self._seg is not None:
            gm = dict(zip(self._prog.arg_names, mask))
            cots = None if out_cots is None else tuple(
                c._handle if isinstance(c, NDArray) else c for c in out_cots)
            outs, new_aux_map, gmap = self._seg.run(
                dict(zip(self._prog.arg_names, args)),
                dict(zip(self._prog.aux_names, aux)),
                keys, bool(is_train), grad_mask=gm, out_cots=cots)
            new_aux = tuple(new_aux_map[n] for n in self._prog.aux_names)
            grads, mask = self._seg_grads(gmap, mask)
        else:
            fn = self._prog._jit_fwd_bwd(bool(is_train), mask)
            if out_cots is None:
                structs = jax.eval_shape(self._prog._jit_forward(bool(is_train)),
                                         args, aux, keys)[0]
                cots = tuple(jnp.ones(s.shape, s.dtype) for s in structs)
            else:
                cots = tuple(c._handle if isinstance(c, NDArray) else c
                             for c in out_cots)
            outs, new_aux, grads = fn(args, aux, keys, cots)
        if is_train:
            for nd_, na in zip(self.aux_arrays, new_aux):
                nd_._handle = na
        self._prof_toc(t0, "_fwd_bwd", (outs, grads))
        self.outputs = [NDArray(o) for o in outs]
        if grads:
            self._write_grads(grads, mask)
        if self._monitor_active():
            self._fire_monitor(args, aux, keys, is_train, self.outputs)
        return self.outputs

    # -- misc API parity -------------------------------------------------
    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def export_compiled(self, path, input_names=("data",),
                        input_dtypes=None, append=False):
        """Write a serialized AOT deploy artifact (see deploy.py).

        The bound arg arrays become the artifact's weights; ``input_names``
        stay runtime inputs.  The result loads via
        deploy.ServedProgram.load (or the C ABI's MXPredCreateFromServed)
        and runs with no symbol layer or tracing."""
        from .deploy import export_compiled as _export
        unknown = [n for n in input_names if n not in self.arg_dict]
        if unknown:
            raise MXNetError("export_compiled: unknown inputs %s" % unknown)
        const_args = {n: arr.asnumpy() for n, arr in self.arg_dict.items()
                      if n not in input_names}
        aux = tuple(a._handle for a in self.aux_arrays)
        input_shapes = {n: self.arg_dict[n].shape for n in input_names}
        return _export(self._prog, const_args, aux, list(input_names),
                       input_shapes, path, input_dtypes, append=append)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._handle = self._commit(
                    arr._handle.astype(self.arg_dict[name]._handle.dtype))
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" not in arguments" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._handle = self._commit(
                        arr._handle.astype(self.aux_dict[name]._handle.dtype))
                elif not allow_extra_params:
                    raise MXNetError("Found name \"%s\" not in aux" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind for new input shapes; XLA re-specialises automatically
        (the reference's careful memory-sharing rebind is unnecessary —
        buffers are XLA-managed)."""
        new_args = {}
        for n, arr in self.arg_dict.items():
            if n in kwargs:
                new_args[n] = nd_zeros(kwargs[n], dtype=arr.dtype,
                                       ctx=self._ctx)
            else:
                new_args[n] = arr
        grads = {n: nd_zeros(new_args[n].shape, dtype=new_args[n].dtype,
                             ctx=self._ctx)
                 for n, g in self.grad_dict.items() if g is not None}
        return Executor(self._symbol, self._ctx, new_args, args_grad=grads,
                        grad_req=self.grad_req, aux_states=self.aux_dict,
                        program=self._prog)

    @property
    def ctx_group_devices(self):
        """Devices of the ctx_group segments, in execution order, or None
        when the graph runs unsegmented on one device (public view of the
        placement result — the PlaceDevice pass outcome)."""
        if self._seg is None:
            return None
        return [s.device for s in self._seg.segments]

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a (name, NDArray) callback fired after each forward.

        monitor_all=False taps only graph outputs; True taps EVERY node
        output (the reference graph_executor.cc:121 behavior) by running
        the instrumented forward program."""
        self._monitor_callback = callback
        self._monitor_all = monitor_all

    def _monitor_active(self):
        if self._monitor_callback is None:
            return False
        gate = getattr(self._monitor_callback, "monitor_active", None)
        return gate() if gate is not None else True

    def _fire_monitor(self, args, aux, keys, is_train, outs, taps=None):
        """Invoke the monitor callback on outputs, or on every node output
        when monitor_all.  taps: precomputed node outputs from a tapped
        forward; when absent under monitor_all an extra tapped forward runs
        (monitor is a debug tool and Monitor.tic gates it to every Nth
        batch)."""
        if self._monitor_all and self._seg is None:
            if taps is None:
                _, _, taps = self._prog._jit_forward_tapped(bool(is_train))(
                    args, aux, keys)
            for n, t in zip(self._prog.tap_names(), taps):
                self._monitor_callback(n, NDArray(t))
        else:
            for n, o in zip(self._symbol.list_outputs(), outs):
                self._monitor_callback(n, o)

    def debug_str(self):
        return self._symbol.debug_str()
