"""Analytic cost model over optimized HLO text.

The static half of the performance attribution plane (the dynamic half —
telemetry histograms and the span split — lives in
:mod:`mxnet_tpu.telemetry.perf`).  Given the optimized HLO of a compiled
program this module computes, WITHOUT executing anything:

* **analytic FLOPs** — dot/convolution contractions from their shapes
  (2·|out|·K), elementwise arithmetic at one flop per output element,
  reduces at one flop per input element; transcendentals counted in
  their own bucket the way ``HloCostAnalysis`` does.  Validated against
  ``Compiled.cost_analysis()`` within 5% on seeded programs
  (tests/test_perf_attribution.py).
* **instruction bytes by op class × dtype** — every instruction's
  result bytes grouped by ``(opcode, dtype)``: the accounting PERF.md
  r4/r5 derived by hand ("+4.9 GB f32 add around every BatchNorm") now
  computed mechanically, with the f32-vs-bf16 split and top-N
  contributors a perf round starts from.
* **collective payloads** — via :func:`parallel.audit
  .collective_accounting` (one parser, already CI-validated to 1.00× of
  the analytic ring model at dp8).
* **collective/compute overlap** — walks each computation's instruction
  schedule and reports what fraction of collective payload bytes is
  issued async (``-start``/``-done``) with real compute between start
  and done: the standing instrument behind ROADMAP item 2's "spans
  prove the overlap" criterion.  Synchronous collectives are by
  construction 0% overlapped.
* **roofline** — peak-normalized compute/HBM/collective times and which
  roof binds, against per-chip peaks (see :func:`chip_peaks`).

"A Learned Performance Model for TPUs" (PAPERS.md) starts from exactly
these analytic features; TVM automates the same accounting with a
measurement harness.  This module is the feature extractor both
directions share.
"""
from __future__ import annotations

import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["HloInstr", "iter_instructions", "analytic_flops",
           "instruction_bytes", "bytes_by_dtype", "top_contributors",
           "collective_compute_overlap", "chip_peaks", "roofline",
           "entry_io_bytes", "memory_breakdown", "predicted_peak_bytes"]

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"(pred|bf16|f8e4m3fn|f8e5m2|[sufc]\d+)\[([\d,]*)\]")

# instruction line: `[ROOT ]%name = TYPE opcode(operands...), attrs...`
# (same shape as parallel/audit.py's collective matcher, kept permissive:
# TYPE may be a tuple of shapes, opcode is the lowercase HLO op name)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\(?.+?\)?)\s+([a-z][\w\-]*)\(")

# one flop per output element (XLA HloCostAnalysis HandleElementwiseOp)
_ELEMENTWISE = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "negate", "abs", "sign", "floor", "ceil", "round-nearest-afz",
    "round-nearest-even", "compare", "select", "clamp", "and", "or",
    "xor", "not", "shift-left", "shift-right-arithmetic",
    "shift-right-logical", "remainder", "atan2", "convert", "is-finite",
})

# counted in HloCostAnalysis's transcendental bucket, not flops
_TRANSCENDENTAL = frozenset({
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "tanh", "sqrt", "rsqrt", "cbrt", "power", "logistic", "sine",
    "cosine", "tan", "erf",
})

_COLLECTIVE_BASES = ("all-reduce", "reduce-scatter", "all-gather",
                     "all-to-all", "collective-permute")

# opcodes that do real work between an async collective's start and done
# (data movement like copy/bitcast/tuple does not hide latency)
_COMPUTE_OPS = frozenset(
    {"dot", "convolution", "fusion", "custom-call", "reduce",
     "reduce-window", "scatter", "gather", "sort", "while", "call",
     "conditional", "cholesky", "triangular-solve"}
    | _ELEMENTWISE | _TRANSCENDENTAL)


def _elems(dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def _shape_tokens(expr: str) -> List[Tuple[str, int]]:
    """Every (dtype, element_count) in a type expression (handles
    tuples)."""
    return [(dt, _elems(dims)) for dt, dims in _SHAPE_RE.findall(expr)]


def _type_bytes(expr: str) -> int:
    return sum(n * _DTYPE_BYTES.get(dt, 4) for dt, n in _shape_tokens(expr))


def _balanced_operands(line: str, open_idx: int) -> Tuple[str, str]:
    """Split an instruction line at the opcode's argument list: returns
    (operands_text, trailing_attrs_text).  ``open_idx`` is the index of
    the opening paren."""
    depth = 0
    for i in range(open_idx, len(line)):
        ch = line[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return line[open_idx + 1:i], line[i + 1:]
    return line[open_idx + 1:], ""


class HloInstr:
    """One parsed HLO instruction."""

    __slots__ = ("name", "opcode", "result_type", "result_dtype",
                 "result_bytes", "operands", "operand_shapes", "attrs",
                 "computation")

    def __init__(self, name, opcode, result_type, operands, attrs,
                 computation):
        self.name = name
        self.opcode = opcode
        self.result_type = result_type
        toks = _SHAPE_RE.findall(result_type)
        self.result_dtype = toks[0][0] if toks else "?"
        self.result_bytes = _type_bytes(result_type)
        self.operands = operands
        # [(dtype, [dims...]), ...] in operand order
        self.operand_shapes = [
            (dt, [int(d) for d in dims.split(",") if d])
            for dt, dims in _SHAPE_RE.findall(operands)]
        self.attrs = attrs
        self.computation = computation

    def __repr__(self):
        return "<HloInstr %s = %s (%s)>" % (self.name, self.opcode,
                                            self.result_type)


def iter_instructions(hlo_text: str) -> Iterator[HloInstr]:
    """Parse every instruction line of an HLO module dump, tracking which
    computation (ENTRY, fused_computation, region, ...) each belongs to.
    Fusion bodies are listed as their own computations, so their inner
    dot/convolution instructions are visible — which is exactly what the
    per-op-class accounting wants."""
    computation = ""
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and ("->" in stripped
                                       or stripped.startswith("ENTRY")):
            head = stripped.split("(", 1)[0].strip()
            computation = head.lstrip("%") or "?"
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rtype, opcode = m.groups()
        operands, attrs = _balanced_operands(line, m.end() - 1)
        yield HloInstr(name, opcode, rtype, operands, attrs, computation)


# ---------------------------------------------------------------------------
# analytic FLOPs
# ---------------------------------------------------------------------------

def _dot_flops(ins: HloInstr) -> int:
    out_elems = sum(n for _, n in _shape_tokens(ins.result_type))
    m = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", ins.attrs)
    if not m or not ins.operand_shapes:
        return 2 * out_elems          # degenerate: no contraction info
    lhs_dims = ins.operand_shapes[0][1]
    k = 1
    for idx in (int(d) for d in m.group(1).split(",") if d):
        if idx < len(lhs_dims):
            k *= lhs_dims[idx]
    return 2 * out_elems * max(1, k)


def _parse_window(attrs: str, n: int):
    """(size, stride, pad_lo, pad_hi, lhs_dilate, rhs_dilate) per spatial
    dim from a ``window={...}`` spec; None when unparsable."""
    m = re.search(r"window=\{([^}]*)\}", attrs)
    fields = {}
    if m:
        for part in m.group(1).split():
            if "=" in part:
                k, v = part.split("=", 1)
                fields[k] = v

    def ints(key, default):
        v = fields.get(key)
        if v is None:
            return [default] * n
        return [int(t) for t in v.split("x") if t]

    size = ints("size", 1)
    if len(size) != n:
        return None
    pad = fields.get("pad")
    if pad is None:
        plo, phi = [0] * n, [0] * n
    else:
        plo, phi = [], []
        for t in pad.split("x"):
            lo, hi = t.split("_")
            plo.append(int(lo))
            phi.append(int(hi))
        if len(plo) != n:
            return None
    return (size, ints("stride", 1), plo, phi,
            ints("lhs_dilate", 1), ints("rhs_dilate", 1))


def _dim_valid_taps(I, k, plo, phi, s, ld, rd):
    """Count (output position, kernel tap) pairs that land on a real
    input element along one spatial dim — in bounds AND not a zero hole
    interleaved by lhs dilation.  This is the per-dim factor XLA's
    HloCostAnalysis multiplies into conv flops, so padded borders and
    strided-conv gradients (lhs_dilate) cost what they actually cost."""
    Id = (I - 1) * ld + 1 if I > 0 else 0
    ke = (k - 1) * rd + 1
    O = (Id + plo + phi - ke) // s + 1
    valid = 0
    for o in range(max(0, O)):
        start = o * s - plo
        for j in range(k):
            pos = start + j * rd
            if 0 <= pos < Id and pos % ld == 0:
                valid += 1
    return valid


def _conv_flops(ins: HloInstr) -> int:
    """2 · batch · out_features · kernel_in_features · valid spatial
    taps, matching ``HloCostAnalysis::HandleConvolution`` (grouping folds
    in through the kernel's input-feature extent)."""
    out_toks = _SHAPE_RE.findall(ins.result_type)
    out_elems = sum(n for _, n in _shape_tokens(ins.result_type))
    m = re.search(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)", ins.attrs)
    if not m or len(ins.operand_shapes) < 2 or not out_toks:
        return 2 * out_elems
    lhs_spec, ker_spec, out_spec = m.groups()
    lhs = ins.operand_shapes[0][1]
    ker = ins.operand_shapes[1][1]
    out_dims = [int(d) for d in out_toks[0][1].split(",") if d]
    if len(lhs) != len(lhs_spec) or len(ker) != len(ker_spec) \
            or len(out_dims) != len(out_spec):
        return 2 * out_elems
    digits = [c for c in lhs_spec if c.isdigit()]
    win = _parse_window(ins.attrs, len(digits))
    if win is None:
        return 2 * out_elems
    size, stride, plo, phi, ld, rd = win
    batch = out_dims[out_spec.index("b")] if "b" in out_spec else 1
    out_f = out_dims[out_spec.index("f")] if "f" in out_spec else 1
    ker_i = ker[ker_spec.index("i")] if "i" in ker_spec else 1
    valid = 1
    for si, c in enumerate(digits):
        valid *= _dim_valid_taps(lhs[lhs_spec.index(c)], size[si],
                                 plo[si], phi[si], stride[si], ld[si],
                                 rd[si])
    return 2 * batch * out_f * ker_i * valid


def analytic_flops(hlo_text: str) -> Dict[str, float]:
    """``{"flops": total, "transcendentals": total, "by_op": {...}}`` —
    the pre-execution FLOP model over the optimized module.  Note: a
    while-loop body is counted ONCE (trip counts are dynamic); the
    repo's hot programs are scan-free unrolled steps where this is
    exact."""
    flops = 0
    trans = 0
    by_op: Dict[str, float] = {}
    for ins in iter_instructions(hlo_text):
        op = ins.opcode
        base = op[:-len("-start")] if op.endswith("-start") else op
        if op == "dot":
            f = _dot_flops(ins)
        elif op == "convolution":
            f = _conv_flops(ins)
        elif base in ("all-reduce", "reduce-scatter"):
            # HloCostAnalysis charges the reduction one flop per output
            # element; '-done' carries no work of its own
            f = sum(n for _, n in _shape_tokens(ins.result_type))
        elif op in _ELEMENTWISE:
            f = sum(n for _, n in _shape_tokens(ins.result_type))
        elif op in ("reduce", "reduce-window"):
            # one flop per reduced input element (first operand)
            f = _prod(ins.operand_shapes[0][1]) if ins.operand_shapes \
                else sum(n for _, n in _shape_tokens(ins.result_type))
        elif op in _TRANSCENDENTAL:
            trans += sum(n for _, n in _shape_tokens(ins.result_type))
            continue
        else:
            continue
        flops += f
        by_op[op] = by_op.get(op, 0) + f
    return {"flops": float(flops), "transcendentals": float(trans),
            "by_op": {k: float(v) for k, v in
                      sorted(by_op.items(), key=lambda kv: -kv[1])}}


def _prod(dims):
    n = 1
    for d in dims:
        n *= d
    return n


# ---------------------------------------------------------------------------
# instruction bytes by op class × dtype
# ---------------------------------------------------------------------------

_SKIP_BYTE_OPS = frozenset({
    # zero-cost views / bookkeeping: counting them as byte traffic would
    # double every value once per alias
    "bitcast", "tuple", "get-tuple-element", "parameter", "constant",
    "after-all", "partition-id", "replica-id", "opt-barrier",
})


def instruction_bytes(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Result bytes per op class, split by dtype:
    ``{opcode: {dtype: bytes}}``.  This is "instruction bytes" in the
    PERF.md r4/r5 sense — a per-op-class traffic proxy over the whole
    module (fusion bodies included), NOT the deduplicated HBM footprint
    (use ``Compiled.cost_analysis()['bytes accessed']`` for the roofline
    number)."""
    out: Dict[str, Dict[str, int]] = {}
    for ins in iter_instructions(hlo_text):
        if ins.opcode in _SKIP_BYTE_OPS or not ins.result_bytes:
            continue
        slot = out.setdefault(ins.opcode, {})
        slot[ins.result_dtype] = slot.get(ins.result_dtype, 0) \
            + ins.result_bytes
    return out


def bytes_by_dtype(per_class: Dict[str, Dict[str, int]]) -> Dict[str, int]:
    """Collapse the per-class table to the f32-vs-bf16 (etc.) split."""
    out: Dict[str, int] = {}
    for dts in per_class.values():
        for dt, b in dts.items():
            out[dt] = out.get(dt, 0) + b
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


def top_contributors(per_class: Dict[str, Dict[str, int]],
                     n: int = 10) -> List[Dict]:
    """The top-N ``(op class, dtype)`` byte contributors, largest
    first — the "name the top-3" table a perf round opens with."""
    flat = [{"op": op, "dtype": dt, "bytes": b}
            for op, dts in per_class.items() for dt, b in dts.items()]
    flat.sort(key=lambda e: -e["bytes"])
    return flat[:n]


# ---------------------------------------------------------------------------
# memory: entry-signature prediction + compiled breakdown
# ---------------------------------------------------------------------------

_ENTRY_RE = re.compile(r"^\s*ENTRY\s+%?[\w.\-]+\s*")


def entry_io_bytes(hlo_text: str) -> Dict[str, int]:
    """Predicted argument/output bytes of a module from its ENTRY
    signature alone: ``{"argument_bytes", "output_bytes"}``.

    This is the costmodel side of the memory reconciliation — the
    numbers ``Compiled.memory_analysis()`` reports as
    ``argument_size_in_bytes``/``output_size_in_bytes`` re-derived from
    the HLO text (they differ only by layout padding), so the
    attribution report can cross-check the parser against XLA the same
    way the FLOP model is cross-checked against ``cost_analysis()``."""
    for line in hlo_text.splitlines():
        if not _ENTRY_RE.match(line):
            continue
        open_idx = line.find("(")
        if open_idx < 0:
            continue
        params, rest = _balanced_operands(line, open_idx)
        out_type = rest.split("->", 1)[1] if "->" in rest else ""
        return {"argument_bytes": _type_bytes(params),
                "output_bytes": _type_bytes(out_type.split("{")[0])}
    return {"argument_bytes": 0, "output_bytes": 0}


def memory_breakdown(compiled_or_stats) -> Dict[str, int]:
    """Normalize ``Compiled.memory_analysis()`` (or an already-fetched
    ``CompiledMemoryStats``) into plain ints:
    ``{argument,output,temp,alias,generated_code,peak}_bytes``.

    ``peak_bytes`` follows XLA's accounting: arguments + outputs +
    temps − aliased bytes (a donated train step aliases params/momenta
    in-place, so its peak is ~1× state, not 2×).  Empty dict when the
    executable cannot report (some deserialized AOT artifacts)."""
    stats = compiled_or_stats
    if hasattr(stats, "memory_analysis"):
        try:
            stats = stats.memory_analysis()
        except Exception:
            return {}
    if stats is None:
        return {}
    def grab(field):
        try:
            return int(getattr(stats, field))
        except (AttributeError, TypeError, ValueError):
            return 0
    out = {
        "argument_bytes": grab("argument_size_in_bytes"),
        "output_bytes": grab("output_size_in_bytes"),
        "temp_bytes": grab("temp_size_in_bytes"),
        "alias_bytes": grab("alias_size_in_bytes"),
        "generated_code_bytes": grab("generated_code_size_in_bytes"),
    }
    if not any(out.values()):
        return {}
    out["peak_bytes"] = max(0, out["argument_bytes"] + out["output_bytes"]
                            + out["temp_bytes"] - out["alias_bytes"])
    return out


def predicted_peak_bytes(state_bytes: float, batch_bytes: float = 0.0,
                         temp_bytes: float = 0.0,
                         donated: bool = True) -> int:
    """Pre-compile peak-HBM prediction for a training-step-shaped
    program (the GC501 input): persistent state (params + optimizer +
    aux) once when the update donates its buffers, TWICE when it does
    not (old and new live simultaneously — the GC202 hazard), plus the
    batch and whatever temp estimate the caller has (0 before a
    compile; ``memory_breakdown()['temp_bytes']`` after one)."""
    factor = 1.0 if donated else 2.0
    return int(factor * float(state_bytes) + float(batch_bytes)
               + float(temp_bytes))


# ---------------------------------------------------------------------------
# collective/compute overlap
# ---------------------------------------------------------------------------

# ops with enough arithmetic to hide a transfer behind (MXU-class work or
# nested control flow that contains it).  Deliberately excludes fusions
# and elementwise: a bookkeeping scatter next to a boundary ppermute must
# not read as "the hop is hidden" (exactly the pre-fix GPipe schedule).
_HEAVY_COMPUTE_OPS = frozenset({
    "dot", "convolution", "custom-call", "while", "call", "conditional",
    "cholesky", "triangular-solve",
})

_OPERAND_REF_RE = re.compile(r"%([\w.\-]+)")


def _pipelined_sync_collectives(instrs: List[HloInstr]) -> Dict[str, bool]:
    """For each SYNC collective in one computation: is there at least one
    heavy compute instruction that is neither an ancestor nor a
    descendant of it?  If so the transfer has real work to hide behind —
    an async backend (TPU converts these to ``-start``/``-done`` pairs)
    overlaps it; a schedule where every collective sits on the critical
    path between its producers and consumers cannot be overlapped by ANY
    scheduler.  Returns ``{instr_name: pipelined}``."""
    by_name = {ins.name: i for i, ins in enumerate(instrs)}
    deps: List[List[int]] = []
    users: List[List[int]] = [[] for _ in instrs]
    for i, ins in enumerate(instrs):
        dd = []
        for ref in _OPERAND_REF_RE.findall(ins.operands):
            j = by_name.get(ref)
            if j is not None and j != i:
                dd.append(j)
                users[j].append(i)
        deps.append(dd)
    heavy = [i for i, ins in enumerate(instrs)
             if ins.opcode in _HEAVY_COMPUTE_OPS]
    out = {}
    for c, ins in enumerate(instrs):
        if ins.opcode not in _COLLECTIVE_BASES:
            continue
        related = {c}
        # reverse BFS over operands (ancestors) + forward over users
        for seed, edges in ((c, deps), (c, users)):
            todo = [seed]
            seen = {seed}
            while todo:
                cur = todo.pop()
                for nxt in edges[cur]:
                    if nxt not in seen:
                        seen.add(nxt)
                        todo.append(nxt)
            related |= seen
        out[ins.name] = any(h not in related for h in heavy)
    return out


def collective_compute_overlap(hlo_text: str) -> Dict:
    """Static overlap instrument: of the module's collective payload
    bytes, how much has real compute to hide behind?

    Two classifications feed ``overlapped_bytes``:

    * **async** — an explicit ``-start`` whose matching ``-done`` has at
      least one compute instruction scheduled in between (XLA already
      realized the overlap; TPU HLO).
    * **pipelined** — a synchronous collective whose computation holds
      at least one heavy compute op (dot/conv-class) that is neither its
      ancestor nor its descendant: the schedule is double-buffered, so a
      backend with async collectives hides the transfer behind that
      compute.  This is how overlap is proven on backends (XLA:CPU — the
      dryrun audit) that never emit ``-start``/``-done``: a collective
      on the critical path between its producers and consumers (the
      pre-fix GPipe boundary hop) cannot be overlapped by ANY scheduler
      and counts 0.

    Returns ``{"collective_bytes", "overlapped_bytes", "overlap_pct",
    "async_ops", "sync_ops", "pipelined_ops", "by_kind"}``;
    ``overlap_pct`` is None when the program has no collectives."""
    total = 0
    overlapped = 0
    async_ops = 0
    sync_ops = 0
    pipelined_ops = 0
    by_kind: Dict[str, Dict[str, int]] = {}
    # per-computation schedule walk
    open_starts: Dict[Tuple[str, str], dict] = {}
    sync_payload: Dict[Tuple[str, str], Tuple[str, int]] = {}
    per_comp: Dict[str, List[HloInstr]] = {}

    def kind_slot(kind):
        return by_kind.setdefault(kind, {"bytes": 0, "overlapped": 0,
                                         "async": 0, "sync": 0,
                                         "pipelined": 0})

    for ins in iter_instructions(hlo_text):
        per_comp.setdefault(ins.computation, []).append(ins)
        op = ins.opcode
        base = op
        is_start = op.endswith("-start")
        is_done = op.endswith("-done")
        if is_start:
            base = op[:-len("-start")]
        elif is_done:
            base = op[:-len("-done")]
        if base in _COLLECTIVE_BASES:
            if is_done:
                # match by operand reference to the -start's name
                for (comp, sname), rec in list(open_starts.items()):
                    if comp == ins.computation and \
                            "%" + sname in ins.operands:
                        if rec["compute_between"]:
                            overlapped += rec["bytes"]
                            kind_slot(base)["overlapped"] += rec["bytes"]
                        del open_starts[(comp, sname)]
                        break
                continue
            payload = _type_bytes(ins.operands)
            total += payload
            slot = kind_slot(base)
            slot["bytes"] += payload
            if is_start:
                async_ops += 1
                slot["async"] += 1
                open_starts[(ins.computation, ins.name)] = {
                    "bytes": payload, "compute_between": False}
            else:
                sync_ops += 1
                slot["sync"] += 1
                sync_payload[(ins.computation, ins.name)] = (base, payload)
            continue
        if op in _COMPUTE_OPS:
            for rec in open_starts.values():
                rec["compute_between"] = True
    # second pass: schedulable overlap for the sync collectives
    if sync_payload:
        pipelined_by_comp = {
            comp: _pipelined_sync_collectives(instrs)
            for comp, instrs in per_comp.items()
            if any(c == comp for c, _ in sync_payload)}
        for (comp, name), (base, payload) in sync_payload.items():
            if pipelined_by_comp.get(comp, {}).get(name):
                pipelined_ops += 1
                overlapped += payload
                slot = kind_slot(base)
                slot["overlapped"] += payload
                slot["pipelined"] += 1
    return {
        "collective_bytes": total,
        "overlapped_bytes": overlapped,
        "overlap_pct": round(100.0 * overlapped / total, 2) if total
        else None,
        "async_ops": async_ops,
        "sync_ops": sync_ops,
        "pipelined_ops": pipelined_ops,
        "by_kind": by_kind,
    }


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

def chip_peaks() -> Dict[str, float]:
    """Per-chip peak rates the roofline normalizes against.  Defaults are
    TPU v5e (bf16): 197 TFLOP/s, 819 GB/s HBM, 2×45 GB/s ICI per link —
    override with ``BENCH_PEAK_TFLOPS`` / ``MXNET_TPU_PEAK_HBM_GBS`` /
    ``MXNET_TPU_PEAK_ICI_GBS`` (bench.py already owns the first knob; the
    attribution plane reads the same one so MFU can never disagree)."""
    def envf(name, default):
        try:
            return float(os.environ[name])
        except (KeyError, ValueError):
            return default
    return {
        "flops": envf("BENCH_PEAK_TFLOPS", 197.0) * 1e12,
        "hbm_bytes_s": envf("MXNET_TPU_PEAK_HBM_GBS", 819.0) * 1e9,
        "ici_bytes_s": envf("MXNET_TPU_PEAK_ICI_GBS", 90.0) * 1e9,
    }


def roofline(flops: float, hbm_bytes: float, collective_wire_bytes: float,
             peaks: Optional[Dict[str, float]] = None,
             measured_step_s: Optional[float] = None) -> Dict:
    """Peak-normalized component times and the binding roof.

    ``measured_step_s`` (when known) anchors the shares: each share is
    that component's lower-bound time over the measured step, and the
    residue the device math cannot explain is the host-bound share.
    Without a measurement the shares are relative to the slowest
    component (pure static mode)."""
    peaks = peaks or chip_peaks()
    compute_s = flops / peaks["flops"] if peaks["flops"] else 0.0
    hbm_s = hbm_bytes / peaks["hbm_bytes_s"] if peaks["hbm_bytes_s"] \
        else 0.0
    coll_s = collective_wire_bytes / peaks["ici_bytes_s"] \
        if peaks["ici_bytes_s"] else 0.0
    comp = {"compute": compute_s, "hbm": hbm_s, "collective": coll_s}
    device_roof = max(comp.values())
    bound = max(comp, key=comp.get) if device_roof > 0 else "unknown"
    out = {"compute_s": compute_s, "hbm_s": hbm_s, "collective_s": coll_s,
           "device_roof_s": device_roof, "bound": bound,
           "peaks": {k: peaks[k] for k in
                     ("flops", "hbm_bytes_s", "ici_bytes_s")}}
    denom = measured_step_s if measured_step_s else device_roof
    if denom:
        shares = {k: round(v / denom, 4) for k, v in comp.items()}
        if measured_step_s:
            host = max(0.0, 1.0 - device_roof / measured_step_s)
            shares["host"] = round(host, 4)
            if host > 0.5:
                out["bound"] = "host"
            out["measured_vs_analytic"] = round(
                measured_step_s / device_roof, 3) if device_roof else None
        out["shares"] = shares
    return out


def decode_step_model(num_layers: int, hidden: int, vocab: int,
                      slots: int, cached_tokens: int,
                      quant_bits: int = 32) -> Dict[str, float]:
    """Analytic cost of ONE paged decode step (all slots, one token
    each) — the roofline the decode bench and servebench hold measured
    tokens/sec against.

    Decode is weights-bandwidth-bound: every step re-reads every matmul
    weight once (12·L·h² block weights + V·h head at ``quant_bits`` per
    value — weight-only quantization divides exactly this term) and the
    cached K/V once (``cached_tokens`` across all slots, f32 pages),
    while FLOPs are a thin 2·bytes multiply-accumulate over the same
    weights.  Returns flops / weight_bytes / kv_bytes / hbm_bytes per
    step; tokens-per-second roofline = slots / (hbm_bytes / HBM_GB/s).
    """
    h, L, V, S = int(hidden), int(num_layers), int(vocab), int(slots)
    matmul_params = 12 * L * h * h + V * h
    weight_bytes = matmul_params * quant_bits / 8.0 \
        + (V + (L * 4 + 2) * h) * 4.0          # embeddings + LN affine f32
    flops = 2.0 * S * matmul_params \
        + 4.0 * S * int(cached_tokens) / max(S, 1) * h * L  # attn qk+pv
    kv_bytes = 2.0 * L * int(cached_tokens) * h * 4.0      # read k+v
    kv_bytes += 2.0 * L * S * h * 4.0                      # this step's write
    return {"flops": flops, "weight_bytes": weight_bytes,
            "kv_bytes": kv_bytes,
            "hbm_bytes": weight_bytes + kv_bytes + S * V * 4.0}
