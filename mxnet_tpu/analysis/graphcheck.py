"""Graph checker: jaxpr-level SPMD/perf lint (pre-flight Engine 1).

Compiler-style analysis passes over the *traced* program — the spirit of
TVM's graph-level passes (arXiv:1802.04799) applied to correctness: trace
any jittable program (a ShardedTrainer step, a Module forward/backward,
the ring/pipeline/moe entry points) to a ClosedJaxpr and run rule passes
over it.  Everything here is static — no device execution, no compile —
so a mismatched collective schedule is rejected at trace time instead of
burning a pod launch before the PR-2 watchdog turns the hang into a
post-mortem.

Rule catalog (docs/static-analysis.md):

========  =======================  ========  ==================================
id        name                     severity  what it catches
========  =======================  ========  ==================================
GC101     collective-axis-unknown  error     collective over an axis name the
                                             mesh does not define
GC102     cond-divergent-          error     `lax.cond` branches with different
          collectives                        collective schedules — ranks that
                                             take different branches deadlock
GC103     while-collective         warning   collective inside `lax.while_loop`
                                             whose trip count is data-dependent
                                             (rank-divergent counts desync)
GC104     ppermute-bad-perm        error     ppermute perm that is not a
                                             partial bijection / out of range
GC105     axis-groups-asymmetric   error     axis_index_groups that do not
                                             partition the axis into equal
                                             disjoint groups
GC106     collective-in-async-     error     collective primitive inside a
          step                               program contracted to be
                                             collective-free (the dist_async
                                             PS worker step: nothing in it may
                                             put a peer on this rank's
                                             critical path)
GC201     replicated-large-array   warning   large state fully replicated on a
                                             model-parallel mesh
GC202     missing-donation         warning   grad/optimizer buffers not donated
                                             (2x peak HBM)
GC203     reshard-chain            warning   chained sharding constraints that
                                             bounce one value between layouts
                                             on the hot path
GC301     bf16-upcast-compute      warning   bf16 values upcast to f32 and fed
                                             straight into dot/conv (silent 2x
                                             FLOP cost on the MXU)
GC302     weak-type-input          warning   weak-typed scalar inputs that
                                             fragment the jit cache
GC304     collectives-serialized   warning   multi-device program moving real
                                             collective payload with ZERO
                                             compute/transfer overlap: no
                                             async -start/-done pair hides
                                             compute and every sync collective
                                             sits on the critical path between
                                             its producers and consumers (the
                                             PR-6 overlap instrument,
                                             costmodel.collective_compute_
                                             overlap, is the oracle)
GC306     densified-embedding-     warning   a program that contains a routed
          grad                               sharded-embedding lookup (all-to-
                                             all present) yet moves full-table-
                                             sized gradient bytes through ONE
                                             dense all-reduce / all-gather —
                                             the "you densified your embedding
                                             grad" footgun: wire bytes scale
                                             with table size instead of
                                             touched rows
GC401     static-float-attr        warning   per-step float attr (lr/wd/...)
                                             reaching an op as a STATIC jit
                                             key -> recompile every step
GC402     registry-dynamic-gap     warning   registered op schema declares a
                                             per-step param outside its
                                             dynamic_params mechanism
GC403     unhashable-attr          error     op attrs that cannot be hashed
                                             into a jit cache key
GC307     decode-retrace           warning   a decode-shaped program (single-
                                             query attention + in-place cache
                                             write) whose trace CHANGES across
                                             step / sequence-length / batch-
                                             membership changes — the
                                             recompile-per-token trap: every
                                             generated token pays a fresh XLA
                                             compile
GC501     hbm-over-capacity        error     predicted peak HBM (costmodel
                                             state/batch accounting +
                                             ``memory_analysis`` temp bytes)
                                             exceeds per-device capacity —
                                             refused BEFORE dispatch instead
                                             of an opaque RESOURCE_EXHAUSTED
========  =======================  ========  ==================================

The per-step attr names behind GC401/GC402 are the scheduled-hyperparam
set (``lr``, ``wd``, ``rescale_grad``, ``t`` and their multi-tensor
plurals); constant schema floats (epsilon, momentum, beta1/2) are fine as
static keys and are not flagged.
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax

from .report import Finding, Report

try:                                    # jax >= 0.4.36
    from jax.extend import core as _core
except ImportError:                     # older: the classic namespace
    from jax import core as _core

__all__ = ["CollectiveEvent", "collect_collectives", "check_jaxpr",
           "check_fn", "check_collective_free", "check_symbol",
           "check_registry",
           "check_replication", "check_capacity", "check_overlap",
           "check_embedding_grad", "check_decode_retrace",
           "is_decode_shaped", "check_trainer", "check_executor",
           "PER_STEP_ATTRS", "COLLECTIVE_PRIMS"]

# every collective primitive we track (axis_index is deliberately absent:
# it reads the axis env but moves no data and cannot desync)
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
})

# attrs that change every optimizer step; static jit keys on these mean
# one fresh XLA compile per step (registry.dynamic_params is the fix).
# The canonical set lives next to the mechanism it polices.
from ..ops.registry import PER_STEP_PARAMS as PER_STEP_ATTRS  # noqa: E402

_JAXPR_TYPES = (_core.Jaxpr, _core.ClosedJaxpr)


def _as_jaxpr(j):
    """Normalize Jaxpr/ClosedJaxpr to the open Jaxpr."""
    return j.jaxpr if isinstance(j, _core.ClosedJaxpr) else j


def _source(eqn) -> str:
    """file:line of the python call that produced this eqn (best effort)."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return ""
        return "%s:%d" % (frame.file_name, frame.start_line)
    except Exception:
        return ""


def _sub_jaxprs(eqn):
    """Yield (label, jaxpr) for every sub-jaxpr in an eqn's params —
    generic, so scan/cond/while/pjit/shard_map/remat/custom_vjp and any
    future higher-order primitive are all walked."""
    for key, val in sorted(eqn.params.items()):
        if isinstance(val, _JAXPR_TYPES):
            yield key, _as_jaxpr(val)
        elif isinstance(val, (tuple, list)):
            for i, item in enumerate(val):
                if isinstance(item, _JAXPR_TYPES):
                    yield "%s[%d]" % (key, i), _as_jaxpr(item)


def _axes_of(params) -> Tuple:
    """Normalized axis names of a collective eqn (strings only; positional
    axes are device-local and cannot mismatch)."""
    axes = params.get("axes", params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


class CollectiveEvent:
    """One collective eqn, in program order, with its jaxpr path."""

    __slots__ = ("prim", "axes", "path", "params", "source")

    def __init__(self, prim, axes, path, params, source):
        self.prim = prim
        self.axes = axes
        self.path = path
        self.params = params
        self.source = source

    def schedule_key(self):
        """The (kind, axes) pair two ranks must agree on to stay in step."""
        return (self.prim, self.axes)

    def __repr__(self):
        return "<Collective %s axes=%s at %s>" % (self.prim, self.axes,
                                                  self.path or "/")


def collect_collectives(jaxpr_like, path: str = "") -> List[CollectiveEvent]:
    """Ordered collective events of a (Closed)Jaxpr, descending into every
    nested jaxpr (scan/cond/while bodies, shard_map, pjit, remat...).
    Cond branches are labelled ``cond.branches[i]`` so callers can compare
    per-branch schedules."""
    events = []
    jaxpr = _as_jaxpr(jaxpr_like)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            events.append(CollectiveEvent(
                name, _axes_of(eqn.params), path, dict(eqn.params),
                _source(eqn)))
        for label, sub in _sub_jaxprs(eqn):
            sub_path = "%s/%s.%s" % (path, name, label) if path \
                else "%s.%s" % (name, label)
            events.extend(collect_collectives(sub, sub_path))
    return events


# ---------------------------------------------------------------------------
# jaxpr rule passes
# ---------------------------------------------------------------------------

def _walk_jaxprs(jaxpr_like, path: str = ""):
    """Yield (path, jaxpr) for the jaxpr and every nested jaxpr."""
    jaxpr = _as_jaxpr(jaxpr_like)
    yield path, jaxpr
    for eqn in jaxpr.eqns:
        for label, sub in _sub_jaxprs(eqn):
            sub_path = "%s/%s.%s" % (path, eqn.primitive.name, label) \
                if path else "%s.%s" % (eqn.primitive.name, label)
            yield from _walk_jaxprs(sub, sub_path)


def _mesh_axis_sizes(mesh) -> Optional[Dict[str, int]]:
    """Accept a Mesh, a {axis: size} mapping, or an iterable of names."""
    if mesh is None:
        return None
    shape = getattr(mesh, "shape", None)
    if shape is not None and hasattr(shape, "items"):
        return dict(shape.items())
    if hasattr(mesh, "items"):
        return dict(mesh.items())
    return {name: 0 for name in mesh}          # names only, sizes unknown


def _rule_axis_names(events, axis_sizes, rep: Report):
    for ev in events:
        unknown = [a for a in ev.axes if a not in axis_sizes]
        if unknown:
            rep.add(
                "GC101", "error",
                "%s over axis %s which the mesh (axes %s) does not define"
                % (ev.prim, unknown, sorted(axis_sizes)),
                location=ev.source or ev.path,
                fix_hint="use a mesh axis name, or add the axis to the "
                         "mesh this program runs under",
                extra={"path": ev.path, "axes": list(ev.axes)})


def _rule_cond_divergence(jaxpr_like, rep: Report, path: str = ""):
    jaxpr = _as_jaxpr(jaxpr_like)
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "cond":
            branches = eqn.params.get("branches", ())
            schedules = [tuple(ev.schedule_key()
                               for ev in collect_collectives(b))
                         for b in branches]
            if len(set(schedules)) > 1:
                desc = ["branch%d=%s" % (i, [f"{p}@{','.join(a) or '-'}"
                                             for p, a in s])
                        for i, s in enumerate(schedules)]
                rep.add(
                    "GC102", "error",
                    "cond branches carry different collective schedules "
                    "(%s): ranks whose predicate diverges deadlock inside "
                    "the collective — the watchdog would only catch this "
                    "as a live hang" % "; ".join(desc),
                    location=_source(eqn) or (path or "/"),
                    fix_hint="hoist the collective out of the cond, or "
                             "make every branch issue the identical "
                             "collective sequence",
                    extra={"path": path,
                           "schedules": [[list(k) for k in s]
                                         for s in schedules]})
        elif name == "while":
            body = eqn.params.get("body_jaxpr")
            cond_j = eqn.params.get("cond_jaxpr")
            inner = []
            for part in (body, cond_j):
                if part is not None:
                    inner.extend(collect_collectives(part))
            if inner:
                rep.add(
                    "GC103", "warning",
                    "collective %s inside a while_loop: the trip count is "
                    "data-dependent, so ranks can disagree on iteration "
                    "count and desynchronize the schedule"
                    % sorted({ev.prim for ev in inner}),
                    location=_source(eqn) or (path or "/"),
                    fix_hint="prefer lax.scan with a static trip count, "
                             "or make the loop condition provably uniform "
                             "across ranks (e.g. psum the predicate)",
                    extra={"path": path})
        for label, sub in _sub_jaxprs(eqn):
            sub_path = "%s/%s.%s" % (path, name, label) if path \
                else "%s.%s" % (name, label)
            _rule_cond_divergence(sub, rep, sub_path)


def _rule_ppermute(events, axis_sizes, rep: Report):
    for ev in events:
        if ev.prim != "ppermute":
            continue
        perm = list(ev.params.get("perm") or ())
        srcs = [s for s, _ in perm]
        dsts = [d for _, d in perm]
        problems = []
        if len(set(srcs)) != len(srcs):
            problems.append("duplicate sources")
        if len(set(dsts)) != len(dsts):
            problems.append("duplicate destinations (two ranks send to "
                            "one; one transfer is silently dropped)")
        if axis_sizes:
            for axis in ev.axes:
                size = axis_sizes.get(axis) or 0
                if size and any(not (0 <= r < size) for r in srcs + dsts):
                    problems.append("rank outside axis %r of size %d"
                                    % (axis, size))
        if problems:
            rep.add(
                "GC104", "error",
                "ppermute perm %s is invalid: %s" % (perm,
                                                     "; ".join(problems)),
                location=ev.source or ev.path,
                fix_hint="a perm must be a partial bijection over "
                         "[0, axis_size)",
                extra={"path": ev.path, "perm": perm})


def _rule_axis_groups(events, axis_sizes, rep: Report):
    for ev in events:
        groups = ev.params.get("axis_index_groups")
        if not groups:
            continue
        sizes = {len(g) for g in groups}
        flat = [r for g in groups for r in g]
        problems = []
        if len(sizes) > 1:
            problems.append("groups of unequal size %s" % sorted(sizes))
        if len(set(flat)) != len(flat):
            problems.append("a rank appears in two groups")
        if axis_sizes:
            for axis in ev.axes:
                size = axis_sizes.get(axis) or 0
                if size and len(flat) != size:
                    problems.append(
                        "groups cover %d ranks but axis %r has %d — the "
                        "uncovered ranks never enter the collective"
                        % (len(flat), axis, size))
        if problems:
            rep.add(
                "GC105", "error",
                "%s axis_index_groups %s do not partition the axis: %s"
                % (ev.prim, groups, "; ".join(problems)),
                location=ev.source or ev.path,
                fix_hint="groups must be equal-sized, disjoint, and "
                         "cover every rank of the axis",
                extra={"path": ev.path})


def _rule_bf16_upcast(jaxpr_like, rep: Report):
    """bf16 -> f32 converts feeding dot/conv: the matmul silently runs at
    f32 MXU throughput (half the bf16 rate) — almost always an accidental
    upcast, since intentional f32 accumulation uses
    preferred_element_type, not an input convert."""
    import numpy as np
    for path, jaxpr in _walk_jaxprs(jaxpr_like):
        upcast_vars = {}
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "convert_element_type" \
                    and eqn.params.get("new_dtype") == np.dtype("float32") \
                    and str(eqn.invars[0].aval.dtype) == "bfloat16":
                upcast_vars[id(eqn.outvars[0])] = eqn
            elif name in ("dot_general", "conv_general_dilated"):
                for v in eqn.invars:
                    src = upcast_vars.get(id(v))
                    if src is not None:
                        rep.add(
                            "GC301", "warning",
                            "bf16 value upcast to f32 feeds %s directly: "
                            "the contraction runs at f32 rate instead of "
                            "bf16" % name,
                            location=_source(eqn) or path,
                            fix_hint="keep the operand bf16 and request "
                                     "f32 accumulation via "
                                     "preferred_element_type if needed",
                            extra={"path": path})
                        upcast_vars.pop(id(v), None)   # once per convert


def _rule_weak_types(closed, target: str, rep: Report):
    jaxpr = _as_jaxpr(closed)
    for i, v in enumerate(jaxpr.invars):
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            rep.add(
                "GC302", "warning",
                "input %d is a weak-typed %s scalar: a later call with a "
                "strongly-typed value (e.g. restored from checkpoint) "
                "misses the jit cache and recompiles the whole program"
                % (i, aval.dtype),
                location=target,
                fix_hint="pin the dtype at the call site: "
                         "jnp.asarray(x, jnp.float32)",
                extra={"arg_index": i})


def _rule_reshard_chain(jaxpr_like, rep: Report):
    for path, jaxpr in _walk_jaxprs(jaxpr_like):
        constrained = {}           # id(var) -> (sharding str, eqn)
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "sharding_constraint":
                continue
            spec = str(eqn.params.get("sharding"))
            prev = constrained.get(id(eqn.invars[0]))
            if prev is not None and prev[0] != spec:
                rep.add(
                    "GC203", "warning",
                    "value is resharded %s -> %s back to back: each hop "
                    "is a collective copy on the hot path" % (prev[0],
                                                              spec),
                    location=_source(eqn) or path,
                    fix_hint="pick one sharding for the value, or move "
                             "the reshard off the per-step path",
                    extra={"path": path})
            for out in eqn.outvars:
                constrained[id(out)] = (spec, eqn)


def check_jaxpr(jaxpr_like, mesh=None, target: str = "") -> Report:
    """Run every jaxpr-level rule pass over a (Closed)Jaxpr.

    ``mesh``: a jax Mesh, a ``{axis: size}`` dict, or an iterable of axis
    names — enables the axis-existence and rank-range checks."""
    rep = Report("graphcheck", target)
    events = collect_collectives(jaxpr_like)
    axis_sizes = _mesh_axis_sizes(mesh)
    if axis_sizes is not None:
        _rule_axis_names(events, axis_sizes, rep)
    _rule_cond_divergence(jaxpr_like, rep)
    _rule_ppermute(events, axis_sizes, rep)
    _rule_axis_groups(events, axis_sizes, rep)
    _rule_bf16_upcast(jaxpr_like, rep)
    _rule_weak_types(jaxpr_like, target, rep)
    _rule_reshard_chain(jaxpr_like, rep)
    return rep


def check_fn(fn, *example_args, mesh=None, target: str = "",
             **example_kwargs) -> Report:
    """Trace ``fn`` (jitted or raw) with example args/structs and run the
    jaxpr rules.  Tracing only — nothing compiles, nothing executes."""
    closed = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
    return check_jaxpr(closed, mesh=mesh,
                       target=target or getattr(fn, "__name__", "fn"))


def check_collective_free(fn_or_jaxpr, *example_args,
                          target: str = "") -> Report:
    """GC106 over a program CONTRACTED to contain no collectives — the
    dist_async PS worker step (kvstore/worker.py): a worker's compute
    between pull and push must depend only on its own weights and batch,
    so a collective primitive anywhere in its trace is an error (a
    straggler peer would re-enter this rank's critical path, which is
    exactly what the async lane exists to prevent)."""
    if isinstance(fn_or_jaxpr, _JAXPR_TYPES):
        closed = fn_or_jaxpr
    else:
        closed = jax.make_jaxpr(fn_or_jaxpr)(*example_args)
        target = target or getattr(fn_or_jaxpr, "__name__", "fn")
    rep = Report("graphcheck", target)
    for ev in collect_collectives(closed):
        rep.add("GC106", "error",
                "collective `%s` over axes %s in a collective-free "
                "contract program" % (ev.prim, list(ev.axes)),
                location=ev.source or ev.path,
                fix_hint="move the collective out of the async worker "
                         "step, or run this program on the sync lane")
    return rep


# ---------------------------------------------------------------------------
# symbol / registry passes (recompile hazards)
# ---------------------------------------------------------------------------

def check_symbol(symbol, target: str = "") -> Report:
    """GC401/GC403 over a Symbol graph: per-step float attrs reaching ops
    as static jit keys, and attrs that cannot hash into a cache key."""
    from ..executor import GraphProgram
    rep = Report("graphcheck", target or (symbol.name or "symbol"))
    prog = GraphProgram(symbol)
    for node in prog.nodes:
        if node.is_var:
            continue
        try:
            attrs = node.parsed_attrs()
        except Exception:
            continue
        dyn = tuple(node.op.dynamic_params)
        for name, val in attrs.items():
            if name in PER_STEP_ATTRS and isinstance(val, float) \
                    and name not in dyn:
                rep.add(
                    "GC401", "warning",
                    "node %s (op %s) carries per-step attr %s=%r as a "
                    "STATIC jit key: every new value compiles a fresh "
                    "program" % (node.name, node.op.name, name, val),
                    location=node.name,
                    fix_hint="declare %r in the op's dynamic_params so "
                             "it rides as a traced input" % name,
                    extra={"op": node.op.name, "attr": name})
        try:
            hash(attrs.key())
        except TypeError as e:
            rep.add(
                "GC403", "error",
                "node %s (op %s) has attrs that cannot hash into a jit "
                "cache key: %s" % (node.name, node.op.name, e),
                location=node.name,
                fix_hint="attr values must be scalars/strings/tuples "
                         "(lists and dicts are converted; arbitrary "
                         "objects are not)",
                extra={"op": node.op.name})
    return rep


def check_registry(target: str = "ops.registry") -> Report:
    """GC402 over the live operator registry: any op whose schema declares
    a per-step param (lr/wd/rescale_grad/t/...) outside dynamic_params
    will recompile on every optimizer step."""
    from ..ops import registry as _registry
    rep = Report("graphcheck", target)
    seen = set()
    for name in _registry.list_ops():
        op = _registry.get_op(name)
        if id(op) in seen:            # aliases share the Operator
            continue
        seen.add(id(op))
        missing = [p for p in op.params
                   if p in PER_STEP_ATTRS and p not in op.dynamic_params]
        if missing:
            rep.add(
                "GC402", "warning",
                "op %s declares per-step params %s outside its "
                "dynamic_params %s" % (op.name, missing,
                                       list(op.dynamic_params)),
                location="ops/registry:%s" % op.name,
                fix_hint="add them to dynamic_params in the @register "
                         "call so schedules don't recompile the op",
                extra={"op": op.name, "missing": missing})
    return rep


# ---------------------------------------------------------------------------
# sharding/memory passes (need context a jaxpr no longer carries)
# ---------------------------------------------------------------------------

def _replicated_threshold_bytes() -> int:
    try:
        mb = float(os.environ.get("MXNET_TPU_PREFLIGHT_REPLICATED_MB", "8"))
    except ValueError:
        mb = 8.0
    return int(mb * (1 << 20))


def check_replication(entries: Iterable[Tuple], mesh,
                      model_axes: Sequence[str] = (),
                      target: str = "") -> Report:
    """GC201: large arrays fully replicated while a model-parallel axis is
    active.  ``entries`` is ``(name, shape, dtype_itemsize, sharding)``;
    replication along pure-dp meshes is the normal design and not flagged.
    """
    rep = Report("graphcheck", target)
    axis_sizes = _mesh_axis_sizes(mesh) or {}
    active = [a for a in model_axes if axis_sizes.get(a, 1) > 1]
    if not active:
        return rep
    threshold = _replicated_threshold_bytes()
    for name, shape, itemsize, sharding in entries:
        n = 1
        for d in shape:
            n *= int(d)
        nbytes = n * int(itemsize)
        if nbytes < threshold:
            continue
        spec = getattr(sharding, "spec", None)
        fully_replicated = spec is None or all(s is None for s in spec)
        if fully_replicated:
            rep.add(
                "GC201", "warning",
                "%s (%.1f MB) is fully replicated although model-parallel "
                "axes %s are active: every device holds a full copy"
                % (name, nbytes / 1e6, active),
                location=name,
                fix_hint="shard it over a model axis (__shard__ attr / "
                         "PartitionSpec), or accept the HBM cost "
                         "explicitly (raise "
                         "MXNET_TPU_PREFLIGHT_REPLICATED_MB)",
                extra={"bytes": nbytes})
    return rep


def check_capacity(predicted_bytes, capacity_bytes=None, target: str = "",
                   detail: Optional[Dict] = None) -> Report:
    """GC501: pre-flight HBM capacity check — the memory-plane twin of
    the collective-schedule rules.  ``predicted_bytes`` comes from
    :func:`~mxnet_tpu.analysis.costmodel.predicted_peak_bytes` (state +
    batch, plus ``memory_analysis`` temps when a compile happened);
    ``capacity_bytes`` defaults to what the backend/env reports
    (``telemetry.memory.device_capacity_bytes``).  Silently passes when
    either side is unknown — a missing capacity must not block a dev
    box, the TPU allocator reports its own."""
    rep = Report("graphcheck", target)
    if capacity_bytes is None:
        from ..telemetry import memory as _memory
        capacity_bytes = _memory.device_capacity_bytes()
    if not predicted_bytes or not capacity_bytes:
        return rep
    if float(predicted_bytes) <= float(capacity_bytes):
        return rep
    extra = {"predicted_bytes": int(predicted_bytes),
             "capacity_bytes": int(capacity_bytes)}
    if detail:
        extra.update(detail)
    rep.add(
        "GC501", "error",
        "predicted peak HBM %.2f GB exceeds the %.2f GB device capacity "
        "(%.1fx): this program would die in the allocator as an opaque "
        "RESOURCE_EXHAUSTED mid-launch"
        % (predicted_bytes / 1e9, capacity_bytes / 1e9,
           predicted_bytes / capacity_bytes),
        location=target,
        fix_hint="cut the microbatch, enable gradient remat "
                 "(backward_mirror_policy), shard optimizer state "
                 "(shard_optimizer_state=True) or params (__shard__/tp), "
                 "and check buffer donation (GC202)",
        extra=extra)
    return rep


def _overlap_threshold_bytes() -> int:
    try:
        mb = float(os.environ.get("MXNET_TPU_GC304_MIN_MB", "1"))
    except ValueError:
        mb = 1.0
    return int(mb * (1 << 20))


def check_overlap(hlo_text: str, target: str = "",
                  min_bytes: Optional[int] = None) -> Report:
    """GC304: a compiled multi-device program that moves real collective
    payload with ZERO collective/compute overlap — nothing async with
    compute between ``-start``/``-done``, and every synchronous
    collective chained on the critical path between its producers and
    consumers (so no scheduler on any backend could hide the transfer).
    The oracle is the PR-6 static overlap instrument
    (:func:`~mxnet_tpu.analysis.costmodel.collective_compute_overlap`).

    Tiny programs (payload under ``MXNET_TPU_GC304_MIN_MB``, default
    1 MB) are not flagged: hiding microsecond transfers buys nothing and
    toy traces (tpulint's built-in entry points, the test fixtures)
    would drown the signal."""
    from . import costmodel
    rep = Report("graphcheck", target)
    ov = costmodel.collective_compute_overlap(hlo_text)
    threshold = _overlap_threshold_bytes() if min_bytes is None \
        else int(min_bytes)
    total_ops = ov["async_ops"] + ov["sync_ops"]
    if not total_ops or ov["collective_bytes"] < threshold:
        return rep
    if ov["overlapped_bytes"] > 0:
        return rep
    rep.add(
        "GC304", "warning",
        "all %d collectives (%.2f MB payload) run synchronously with "
        "zero compute overlap: every transfer is dead time on the "
        "critical path" % (total_ops, ov["collective_bytes"] / 1e6),
        location=target,
        fix_hint="double-buffer the schedule so each collective's "
                 "operand comes from the previous iteration and its "
                 "result is consumed in the next (parallel/ring.py and "
                 "parallel/pipeline.py are the worked examples), or "
                 "overlap per-tensor collectives with other tensors' "
                 "compute",
        extra={"collective_bytes": ov["collective_bytes"],
               "async_ops": ov["async_ops"], "sync_ops": ov["sync_ops"],
               "pipelined_ops": ov["pipelined_ops"]})
    return rep


def _zero_threshold_bytes() -> int:
    try:
        mb = float(os.environ.get("MXNET_TPU_GC305_MIN_MB", "8"))
    except ValueError:
        mb = 8.0
    return int(mb * (1 << 20))


def check_zero_update(dp_size: int, update_sharded: bool,
                      grad_payload_bytes, target: str = "",
                      min_bytes: Optional[int] = None) -> Report:
    """GC305: a dp-replicated parameter set paying ≥ threshold MB of
    pure-replica gradient all-reduce EVERY step while the ZeRO sharded
    weight update is off.  The reduce-scatter → shard-local update →
    weight all-gather form moves the same wire bytes but runs the
    optimizer at 1/dp FLOPs and state bytes per chip with the gather
    schedulable against other parameters' updates — leaving it off at
    real payloads is measurable money on the table ("Automatic
    Cross-Replica Sharding of Weight Update in Data-Parallel Training").
    Tiny payloads (under ``MXNET_TPU_GC305_MIN_MB``, default 8 MB) are
    not flagged: toy programs and the fixtures would drown the signal."""
    rep = Report("graphcheck", target)
    threshold = _zero_threshold_bytes() if min_bytes is None \
        else int(min_bytes)
    payload = int(grad_payload_bytes or 0)
    if dp_size <= 1 or update_sharded or payload < threshold:
        return rep
    rep.add(
        "GC305", "warning",
        "%.1f MB of gradients all-reduce fully replicated over dp=%d "
        "every step while the sharded weight update is off: each chip "
        "redundantly runs the full optimizer update and holds the full "
        "optimizer state" % (payload / 1e6, dp_size),
        location=target,
        fix_hint="enable the ZeRO update (ShardedTrainer(zero=True) or "
                 "MXNET_TPU_ZERO=1): grads reduce-scatter into dp "
                 "shards, the update runs at 1/dp FLOPs/bytes, new "
                 "weights all-gather back — identical numerics; or "
                 "raise MXNET_TPU_GC305_MIN_MB",
        extra={"grad_payload_bytes": payload, "dp_size": int(dp_size)})
    return rep


def _embedding_threshold_bytes() -> int:
    try:
        mb = float(os.environ.get("MXNET_TPU_GC306_MIN_MB", "8"))
    except ValueError:
        mb = 8.0
    return int(mb * (1 << 20))


def check_embedding_grad(hlo_text: str, table_bytes=None, target: str = "",
                         min_bytes: Optional[int] = None) -> Report:
    """GC306: the densified-embedding-gradient footgun.

    A program that routes a sharded-embedding lookup (the all-to-all
    signature of :mod:`mxnet_tpu.sparse.embedding`) should move gradient
    bytes proportional to *touched rows*; a single dense all-reduce /
    all-gather of full-table-sized payload in the same program means a
    table's gradient was materialized dense — usually a half-migrated
    model that still differentiates a replicated copy of a table, paying
    table-size wire bytes every step.

    ``table_bytes``: per-table GLOBAL byte sizes (defaults to the live
    :func:`~mxnet_tpu.sparse.embedding.live_tables` registry).  The
    flagging threshold is ``max(MXNET_TPU_GC306_MIN_MB, half the
    smallest table)`` so toy MLP grads in the same program never trip
    it.  Payload conventions match ``parallel.audit``: sync ops report
    result bytes, async ``-start`` their operand bytes."""
    from ..parallel.audit import _shape_bytes
    from . import costmodel
    rep = Report("graphcheck", target)
    instrs = list(costmodel.iter_instructions(hlo_text))
    if not any(i.opcode.split("-start")[0] == "all-to-all"
               for i in instrs):
        return rep          # no routed lookup in this program
    if table_bytes is None:
        try:
            from ..sparse.embedding import live_tables
            table_bytes = [b for _n, b in live_tables()]
        except Exception:
            table_bytes = []
    table_bytes = [int(b) for b in (table_bytes or []) if b]
    floor = _embedding_threshold_bytes() if min_bytes is None \
        else int(min_bytes)
    threshold = max(floor, min(table_bytes) // 2) if table_bytes else floor
    for ins in instrs:
        op = ins.opcode
        base = op[:-len("-start")] if op.endswith("-start") else op
        if base not in ("all-reduce", "all-gather") or \
                op.endswith("-done"):
            continue
        payload = _shape_bytes(ins.operands) if op.endswith("-start") \
            else ins.result_bytes
        if payload < threshold:
            continue
        rep.add(
            "GC306", "warning",
            "%s %r moves %.1f MB in ONE dense collective while this "
            "program also routes a sharded-embedding lookup: an "
            "embedding gradient was densified, so wire bytes scale "
            "with table size (%s MB tables live) instead of touched "
            "rows" % (base, ins.name, payload / 1e6,
                      ",".join("%.0f" % (b / 1e6) for b in table_bytes)
                      or "?"),
            location=target,
            fix_hint="differentiate with respect to the looked-up ROWS "
                     "and feed (ids, grad_rows) to ShardedEmbedding."
                     "apply_sgd/apply_adam (the touched-rows lazy "
                     "update); shard the table with __shard__/P(axis) "
                     "instead of replicating it; or raise "
                     "MXNET_TPU_GC306_MIN_MB",
            extra={"payload_bytes": int(payload), "instruction": ins.name,
                   "table_bytes": table_bytes})
    return rep


_CACHE_WRITE_PRIMS = frozenset({"scatter", "dynamic_update_slice",
                                "dynamic-update-slice", "concatenate"})


def is_decode_shaped(jaxpr_like) -> bool:
    """Heuristic decode signature: the program writes in place into a
    cache-like buffer (scatter / dynamic_update_slice) AND contracts a
    query against an operand at least an order of magnitude larger (the
    single-query-vs-cached-K/V shape of decode attention)."""
    has_write = False
    has_sq_attn = False
    for _path, jaxpr in _walk_jaxprs(jaxpr_like):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _CACHE_WRITE_PRIMS:
                has_write = True
            elif name == "dot_general" and len(eqn.invars) >= 2:
                sizes = []
                for v in eqn.invars[:2]:
                    aval = getattr(v, "aval", None)
                    n = 1
                    for d in getattr(aval, "shape", ()) or ():
                        n *= int(d)
                    sizes.append(n)
                if min(sizes) and max(sizes) >= 16 * min(sizes):
                    has_sq_attn = True
    return has_write and has_sq_attn


def check_decode_retrace(step_fn, args_a, args_b,
                         target: str = "") -> Report:
    """GC307: the recompile-per-token trap.

    ``args_a`` / ``args_b`` are two example argument tuples for the SAME
    decode step at different generation states (another token position,
    another sequence length, another batch membership).  A correctly
    built step (fixed cache shapes, position/length as traced DATA)
    traces to the identical jaxpr for both; a step that bakes either
    into the trace — python-int positions as static args, a cache that
    grows by concatenation, per-length padding — produces different
    avals or different jaxprs, which at serving time means one fresh XLA
    compile per generated token.  Only decode-shaped programs
    (:func:`is_decode_shaped`) are judged; anything else passes
    silently so the rule can sit on generic entry points."""
    rep = Report("graphcheck", target or "decode")
    try:
        closed_a = jax.make_jaxpr(step_fn)(*args_a)
    except TypeError as e:
        # the step coerces a traced value to a host int (int(pos),
        # shape arithmetic from the position, ...) — under jit that
        # value is a STATIC cache key and every new position recompiles
        rep.add(
            "GC307", "warning",
            "decode step cannot trace with its generation state held "
            "abstract (%s): a step/position/length is consumed as a "
            "host value, so under jit it becomes a static cache key "
            "and every generated token compiles a fresh program"
            % (str(e).splitlines()[0][:160],),
            location=target,
            fix_hint="pass step/position/length as traced int32 arrays "
                     "and index with lax.dynamic_update_slice / "
                     "gather, never int(pos) or pos-derived shapes")
        return rep
    avals_a = [str(v.aval) for v in closed_a.jaxpr.invars]
    if not is_decode_shaped(closed_a):
        return rep
    closed_b = jax.make_jaxpr(step_fn)(*args_b)
    avals_b = [str(v.aval) for v in closed_b.jaxpr.invars]
    if avals_a != avals_b:
        changed = [i for i, (a, b) in enumerate(zip(avals_a, avals_b))
                   if a != b][:4]
        rep.add(
            "GC307", "warning",
            "decode step input SHAPES change with generation state "
            "(args %s: %s -> %s): every step/sequence-length change "
            "recompiles the program — one fresh XLA compile per "
            "generated token"
            % (changed,
               [avals_a[i] for i in changed],
               [avals_b[i] for i in changed]),
            location=target,
            fix_hint="hold K/V in a fixed page pool indexed by a page "
                     "table (serving/decode.PagedKVCache layout) and "
                     "mask by seq_len instead of slicing to it",
            extra={"changed_args": changed})
        return rep
    if str(closed_a) != str(closed_b):
        rep.add(
            "GC307", "warning",
            "decode step traces DIFFERENTLY at two generation states "
            "with identical input shapes: a step/position/length is "
            "baked into the trace as a constant, so every token change "
            "misses the jit cache and recompiles",
            location=target,
            fix_hint="pass the changing value as a traced int32 array "
                     "argument (it must appear in the jaxpr as an input, "
                     "not a literal)")
    return rep


def check_donation(donated: bool, what: str, target: str = "") -> Report:
    """GC202: the training step's state buffers (params/momenta/guard)
    must be donated or the update holds old+new copies live — 2x peak."""
    rep = Report("graphcheck", target)
    if not donated:
        rep.add(
            "GC202", "warning",
            "%s run without buffer donation: the update keeps the old and "
            "new state live simultaneously (2x peak HBM)" % what,
            location=target,
            fix_hint="pass donate_argnums covering params and optimizer "
                     "state to jax.jit")
    return rep


# ---------------------------------------------------------------------------
# whole-program entry points
# ---------------------------------------------------------------------------

def check_trainer(trainer, params, mom, aux, inputs, keys=None,
                  guard=None) -> Tuple[Report, object]:
    """Full pre-flight over a ShardedTrainer's step program.

    Traces the exact raw step function the trainer jits (same remat
    policy, same guard automaton) and runs every pass.  Returns
    ``(report, closed_jaxpr)`` so callers can persist the jaxpr artifact.
    """
    keys = keys if keys is not None else trainer._keys()
    guard = guard if guard is not None else trainer._guard_arrays()
    step_fn = trainer._make_step_fn()
    closed = jax.make_jaxpr(step_fn)(params, mom, aux, inputs, keys, guard)
    target = "ShardedTrainer(%s)" % (trainer.symbol.name or "symbol")
    rep = check_jaxpr(closed, mesh=trainer.spec.mesh, target=target)
    rep.extend(check_symbol(trainer.symbol, target=target))
    rep.extend(check_registry())
    shardings = trainer._param_shardings()
    entries = [(n, trainer._param_shapes.get(n, ()), 4, s)
               for n, s in zip(trainer.param_names, shardings)]
    model_axes = [a for a in (trainer.tp_axis,) if a]
    rep.extend(check_replication(entries, trainer.spec.mesh, model_axes,
                                 target=target))
    rep.extend(check_donation(getattr(trainer, "_step_donated", True),
                              "ShardedTrainer jitted step", target=target))
    # GC305: pure-replica grad all-reduce while the ZeRO update is off
    grad_payload = 0
    for n in trainer.param_names:
        count = 1
        for d in trainer._param_shapes.get(n, ()):
            count *= int(d)
        grad_payload += 4 * count
    rep.extend(check_zero_update(
        trainer.spec.dp_size,
        getattr(trainer, "shard_weight_update", False),
        grad_payload, target=target))
    # GC501: predicted peak HBM (state + batch; the costmodel's donated
    # vs undonated accounting) against the device capacity, BEFORE any
    # buffer is allocated
    from . import costmodel

    def _leaf_bytes(tree):
        import numpy as np
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            total += n * np.dtype(dtype).itemsize
        return total

    state_bytes = _leaf_bytes((params, mom, aux))
    batch_bytes = _leaf_bytes(inputs)
    predicted = costmodel.predicted_peak_bytes(
        state_bytes, batch_bytes,
        donated=getattr(trainer, "_step_donated", True))
    rep.extend(check_capacity(
        predicted, target=target,
        detail={"state_bytes": state_bytes, "batch_bytes": batch_bytes,
                "donated": getattr(trainer, "_step_donated", True)}))
    rep.target = target
    return rep, closed


def check_executor(executor, train: bool = True) -> Tuple[Report, object]:
    """Pre-flight over a bound Executor's fused forward+backward program
    (the Module path).  Traces with the executor's own buffers as shape
    structs; returns ``(report, closed_jaxpr)``."""
    prog = executor._prog
    args = tuple(a._handle for a in executor.arg_arrays)
    aux = tuple(a._handle for a in executor.aux_arrays)
    keys = executor._keys()
    mask = tuple(executor.grad_req.get(n, "null") != "null"
                 for n in prog.arg_names)
    target = "Executor(%s)" % (executor._symbol.name or "symbol")
    fwd = prog._jit_forward(bool(train))
    if any(mask):
        outs, _ = jax.eval_shape(fwd, args, aux, keys)
        cots = tuple(jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs)
        fb = prog._jit_fwd_bwd(bool(train), mask)
        closed = jax.make_jaxpr(fb)(args, aux, keys, cots)
    else:
        closed = jax.make_jaxpr(fwd)(args, aux, keys)
    rep = check_jaxpr(closed, target=target)
    rep.extend(check_symbol(executor._symbol, target=target))
    rep.extend(check_registry())
    rep.target = target
    return rep, closed
