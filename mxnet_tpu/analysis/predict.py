"""Prediction-conformance plane: calibrated pre-flight budgets and
runtime drift verdicts.

The analytic cost model (:mod:`costmodel`) validates hard — collective
bytes at 1.000x, FLOPs/bytes within the 5% CI gate, memory reconciling
at 1.00 — but a roofline lower bound is not a *prediction*: real steps
land above the device roof by a hardware- and program-class-dependent
achievable fraction.  This module closes that gap in three pieces:

* **calibration store** — achievable-fraction coefficients per
  ``device_kind × roofline bucket`` (compute / hbm / collective),
  fitted from the telemetry step histograms (every attribution report
  with a measured step is a calibration sample) and from the committed
  ``PERF_LEDGER.jsonl`` history (the ``*_mfu`` series are exactly the
  compute-bucket fraction).  Persisted under the PR-13 shared cache
  rule (:func:`~mxnet_tpu.compile.paths.cache_location`):
  ``MXNET_TPU_CALIBRATION_CACHE`` overrides, off-values disable, default
  ``~/.cache/mxnet_tpu/calibration.json``.

* **pre-flight budgets** — :func:`predict_budget` composes the cost
  model's FLOPs / HBM bytes / per-axis collective wire / memory
  breakdown with the calibrated fraction into predicted step-time,
  peak-HBM, wire-bytes and throughput budgets, gated against
  ``MXNET_TPU_DEVICE_HBM_GB``-style limits.  ``tpulint --predict``
  runs it over the standard entry points and writes atomic
  ``predict-*.json`` reports into the forensics dir.

* **runtime conformance** — :func:`conformance` compares measured
  histograms against a budget and hands back per-metric
  measured/predicted ratios with a WITHIN / DEGRADED / VIOLATED
  verdict; the bands reuse the benchwatch drawdown-σ machinery
  (``max(σ·noise, floor)`` with the floor at the ~20% agreement
  target).  ``telemetry/perf.py`` folds the section into attribution
  reports, exports ``perf.conformance{entry,metric}`` gauges and a
  Perfetto counter track, and the heartbeat digests carry a per-rank
  conformance column so the fleet view can finger a rank slow against
  its OWN budget, not just against its peers.

"A Learned Performance Model for TPUs" (PAPERS.md) is the blueprint:
a calibrated per-hardware predictor is the prerequisite for every
downstream decision — ROADMAP item 1(b–d) consumes exactly this store.
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Dict, List, Optional

from ..compile.paths import cache_location

__all__ = ["DEFAULT_FRACTION", "achievable_fraction", "budget_table",
           "calibration_store_path", "conformance", "conformance_bands",
           "digest_column", "fit_from_attribution", "fit_from_ledger",
           "load_store", "note_budget", "noted_budget", "predict_budget",
           "predict_decode_budget", "reset", "runtime_conformance",
           "save_report", "save_store", "update_calibration"]

STORE_VERSION = 1
ENV_STORE = "MXNET_TPU_CALIBRATION_CACHE"

# uncalibrated fallback: a real step typically lands near half its
# device roof (host residue, launch gaps, un-overlapped collectives) —
# honest enough to bootstrap, replaced by the first fitted sample
DEFAULT_FRACTION = 0.5

# conformance floor = the repo's ~20% prediction-agreement target; the
# σ multiplier matches the benchwatch gate
CONFORMANCE_FLOOR = 0.20
SIGMA_MULT = 4.0

VERDICTS = ("WITHIN", "DEGRADED", "VIOLATED")

_SEQ = [0]
_LOCK = threading.Lock()
_NOTED: Dict[str, Dict] = {}            # program -> budget of record
_LAST_CONFORMANCE: Dict[str, Dict] = {}  # program -> conformance section


# ---------------------------------------------------------------------------
# calibration store
# ---------------------------------------------------------------------------

def calibration_store_path() -> Optional[str]:
    """On-disk location of the calibration store (PR-13 shared cache
    rule); None when ``MXNET_TPU_CALIBRATION_CACHE`` disables it."""
    return cache_location(ENV_STORE, "calibration.json")


def device_kind() -> str:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def _empty_store() -> Dict:
    return {"version": STORE_VERSION, "fitted_t": None, "entries": {}}


def load_store(path: Optional[str] = None) -> Dict:
    """Read the persisted store (an empty one when missing/disabled/
    corrupt — a broken cache must never break a run)."""
    path = calibration_store_path() if path is None else path
    if not path or not os.path.isfile(path):
        return _empty_store()
    try:
        with open(path) as f:
            store = json.load(f)
    except (OSError, ValueError):
        return _empty_store()
    if not isinstance(store, dict) or \
            not isinstance(store.get("entries"), dict):
        return _empty_store()
    return store


def save_store(store: Dict, path: Optional[str] = None) -> Optional[str]:
    """Atomic write (tmp + fsync + replace); no-op when disabled."""
    path = calibration_store_path() if path is None else path
    if not path:
        return None
    store = dict(store, version=STORE_VERSION, fitted_t=time.time())
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(store, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _key(kind: str, bucket: str) -> str:
    return "%s|%s" % (kind, bucket)


def update_calibration(store: Dict, kind: str, bucket: str,
                       fraction: float, source: str = "measured",
                       weight: int = 1) -> Dict:
    """Fold one achievable-fraction sample into the store entry for
    ``device_kind × bucket`` (running mean over sample count).  The
    fraction is clamped to [1e-4, 1]: a step can never beat its roof,
    and the low end still admits hosts (CPU dev boxes) whose real
    throughput sits far under the modeled accelerator peaks."""
    fraction = min(1.0, max(1e-4, float(fraction)))
    ent = store["entries"].get(_key(kind, bucket))
    if ent is None:
        ent = {"achievable_fraction": fraction, "n": int(weight),
               "source": source}
    else:
        n = int(ent.get("n", 1))
        total = ent["achievable_fraction"] * n + fraction * weight
        n += int(weight)
        ent = {"achievable_fraction": round(total / n, 6), "n": n,
               "source": source if source == ent.get("source")
               else "mixed"}
    store["entries"][_key(kind, bucket)] = ent
    return store


def achievable_fraction(store: Optional[Dict], kind: str,
                        bucket: str) -> Dict:
    """``{"fraction", "n", "source"}`` for a device_kind × roofline
    bucket; falls back to the same device's other buckets' mean, then to
    :data:`DEFAULT_FRACTION` (``source: "default"``)."""
    store = store or _empty_store()
    ent = store["entries"].get(_key(kind, bucket))
    if ent:
        return {"fraction": float(ent["achievable_fraction"]),
                "n": int(ent.get("n", 1)),
                "source": ent.get("source", "measured")}
    same_kind = [e["achievable_fraction"]
                 for k, e in store["entries"].items()
                 if k.startswith(kind + "|")]
    if same_kind:
        return {"fraction": round(statistics.fmean(same_kind), 6),
                "n": 0, "source": "nearest-bucket"}
    return {"fraction": DEFAULT_FRACTION, "n": 0, "source": "default"}


def fit_from_attribution(store: Dict, data: Dict) -> Optional[Dict]:
    """One calibration sample from an attribution report (or its data
    dict): achievable fraction = device_roof_s / measured step, bucketed
    by the DEVICE binding roof (host/input verdicts calibrate the bucket
    the device math picked, not themselves)."""
    roof = (data.get("roofline") or {})
    step = (data.get("step") or {})
    measured = step.get("measured_s")
    device_roof = roof.get("device_roof_s")
    if not measured or not device_roof:
        return None
    comp = {"compute": roof.get("compute_s", 0.0),
            "hbm": roof.get("hbm_s", 0.0),
            "collective": roof.get("collective_s", 0.0)}
    bucket = max(comp, key=comp.get)
    kind = ((data.get("topology") or {}).get("device_kind")
            or device_kind())
    return update_calibration(store, kind, bucket,
                              device_roof / measured, source="telemetry")


def _default_ledger_path() -> str:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "PERF_LEDGER.jsonl")


def fit_from_ledger(store: Optional[Dict] = None,
                    ledger_path: Optional[str] = None,
                    kind: Optional[str] = None) -> Dict:
    """Fit the compute bucket from the committed ledger history: every
    ``*_mfu`` metric IS an achievable-fraction sample (MFU = analytic
    compute_s / measured step for a compute-bound program)."""
    store = load_store() if store is None else store
    ledger_path = ledger_path or _default_ledger_path()
    kind = kind or device_kind()
    samples: List[float] = []
    try:
        with open(ledger_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                for name, v in (e.get("metrics") or {}).items():
                    if name.endswith("_mfu") and \
                            isinstance(v, (int, float)) and 0 < v <= 1:
                        samples.append(float(v))
    except OSError:
        return store
    if samples:
        update_calibration(store, kind, "compute",
                           statistics.median(samples), source="ledger",
                           weight=len(samples))
    return store


# ---------------------------------------------------------------------------
# pre-flight budgets
# ---------------------------------------------------------------------------

def budget_limits() -> Dict[str, float]:
    """Operator-declared ceilings the pre-flight gate checks budgets
    against (absent env -> metric not gated):

    * ``MXNET_TPU_DEVICE_HBM_GB``       peak-HBM ceiling (the memory
      plane's capacity override — ONE knob for GC501 and the budget)
    * ``MXNET_TPU_STEP_BUDGET_MS``      predicted-step ceiling
    * ``MXNET_TPU_WIRE_BUDGET_MB``      per-step collective wire ceiling
    * ``MXNET_TPU_THROUGHPUT_FLOOR``    items/s floor (a budget BELOW
      this is over budget)
    """
    out = {}

    def envf(name):
        try:
            return float(os.environ[name])
        except (KeyError, ValueError):
            return None

    v = envf("MXNET_TPU_DEVICE_HBM_GB")
    if v:
        out["peak_hbm_bytes"] = v * (1 << 30)
    v = envf("MXNET_TPU_STEP_BUDGET_MS")
    if v:
        out["step_time_s"] = v / 1e3
    v = envf("MXNET_TPU_WIRE_BUDGET_MB")
    if v:
        out["wire_bytes_per_step"] = v * 1e6
    v = envf("MXNET_TPU_THROUGHPUT_FLOOR")
    if v:
        out["throughput_per_s"] = v
    return out


def _gate(budget: Dict, limits: Dict) -> List[str]:
    over = []
    for metric, limit in limits.items():
        v = budget.get(metric)
        if v is None:
            continue
        if metric == "throughput_per_s":
            if v < limit:
                over.append(metric)
        elif v > limit:
            over.append(metric)
    return over


def predict_budget(compiled=None, name: str = "program", *,
                   n_devices: int = 1, ring_n: Optional[int] = None,
                   hlo_text: Optional[str] = None, mesh=None,
                   items_per_step: Optional[float] = None,
                   store: Optional[Dict] = None) -> Dict:
    """The pre-flight budget for one program: cost-model features ×
    calibrated achievable fraction -> predicted step-time / peak-HBM /
    wire-bytes / throughput, gated against :func:`budget_limits`.

    ``compiled`` (when given) supplies XLA's deduplicated
    bytes-accessed and the compiled memory breakdown; ``hlo_text``
    alone runs the pure-static path.  The report is remembered as the
    program's budget of record (:func:`note_budget`) so the runtime
    conformance pass compares against exactly what was promised."""
    from . import costmodel
    from ..parallel import audit

    if hlo_text is None:
        hlo_text = compiled.as_text()
    ring_n = ring_n or n_devices

    fl = costmodel.analytic_flops(hlo_text)
    per_class = costmodel.instruction_bytes(hlo_text)
    instr_total = float(sum(b for dts in per_class.values()
                            for b in dts.values()))
    bytes_accessed = None
    if compiled is not None:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            bytes_accessed = float(ca.get("bytes accessed") or 0) or None
        except Exception:
            bytes_accessed = None
    hbm_bytes = bytes_accessed if bytes_accessed else instr_total

    acct = audit.collective_accounting(hlo_text,
                                       mesh=getattr(mesh, "mesh", mesh))
    wire = 0
    for kind_name, info in acct.items():
        wire += audit.collective_wire_bytes(kind_name, info["bytes"],
                                            ring_n)

    roof = costmodel.roofline(fl["flops"], hbm_bytes, float(wire))
    kind = device_kind()
    store = load_store() if store is None else store
    cal = achievable_fraction(store, kind, roof["bound"])
    step_s = (roof["device_roof_s"] / cal["fraction"]
              if roof["device_roof_s"] > 0 else None)

    peak = None
    if compiled is not None:
        peak = costmodel.memory_breakdown(compiled).get("peak_bytes")
    if not peak:
        io = costmodel.entry_io_bytes(hlo_text)
        peak = io["argument_bytes"] + io["output_bytes"]

    budget = {
        "step_time_s": round(step_s, 9) if step_s else None,
        "peak_hbm_bytes": int(peak),
        "wire_bytes_per_step": int(wire),
        "throughput_per_s": round(items_per_step / step_s, 3)
        if (items_per_step and step_s) else None,
    }
    limits = budget_limits()
    report = {
        "kind": "predict_report",
        "program": name,
        "time": time.time(),
        "topology": {"n_devices": int(n_devices), "ring_n": int(ring_n),
                     "device_kind": kind},
        "budget": budget,
        "basis": {
            "flops": fl["flops"],
            "hbm_bytes": float(hbm_bytes),
            "hbm_basis": "cost_analysis" if bytes_accessed
            else "instruction_bytes",
            "device_roof_s": roof["device_roof_s"],
            "compute_s": roof["compute_s"],
            "hbm_s": roof["hbm_s"],
            "collective_s": roof["collective_s"],
            "bound": roof["bound"],
            "peaks": roof["peaks"],
            "achievable_fraction": cal["fraction"],
            "calibration_source": cal["source"],
            "calibration_n": cal["n"],
            "items_per_step": items_per_step,
        },
        "limits": limits,
        "over_budget": _gate(budget, limits),
    }
    note_budget(name, report)
    return report


def predict_decode_budget(num_layers: int, hidden: int, vocab: int,
                          slots: int, cached_tokens: int,
                          quant_bits: int = 32, name: str = "decode",
                          store: Optional[Dict] = None) -> Dict:
    """Decode-entry budget from :func:`costmodel.decode_step_model`
    (weights-bandwidth-bound: no HLO needed) — throughput budget is
    tokens/s across all ``slots``."""
    from . import costmodel

    model = costmodel.decode_step_model(num_layers, hidden, vocab, slots,
                                        cached_tokens,
                                        quant_bits=quant_bits)
    roof = costmodel.roofline(model["flops"], model["hbm_bytes"], 0.0)
    kind = device_kind()
    store = load_store() if store is None else store
    cal = achievable_fraction(store, kind, roof["bound"])
    step_s = (roof["device_roof_s"] / cal["fraction"]
              if roof["device_roof_s"] > 0 else None)
    budget = {
        "step_time_s": round(step_s, 9) if step_s else None,
        "peak_hbm_bytes": int(model["hbm_bytes"]),
        "wire_bytes_per_step": 0,
        "throughput_per_s": round(slots / step_s, 3) if step_s else None,
    }
    limits = budget_limits()
    report = {
        "kind": "predict_report",
        "program": name,
        "time": time.time(),
        "topology": {"n_devices": 1, "ring_n": 1, "device_kind": kind},
        "budget": budget,
        "basis": dict(model, bound=roof["bound"],
                      device_roof_s=roof["device_roof_s"],
                      achievable_fraction=cal["fraction"],
                      calibration_source=cal["source"],
                      calibration_n=cal["n"], items_per_step=slots),
        "limits": limits,
        "over_budget": _gate(budget, limits),
    }
    note_budget(name, report)
    return report


def save_report(report: Dict) -> str:
    """Atomic ``predict-<program>-<pid>-<seq>.json`` into the same
    forensics dir as attribution reports and preflight post-mortems."""
    from ..telemetry import perf as _perf
    d = _perf.report_dir()
    os.makedirs(d, exist_ok=True)
    with _LOCK:
        _SEQ[0] += 1
        seq = _SEQ[0]
    safe = "".join(ch if (ch.isalnum() or ch in "._-") else "_"
                   for ch in report.get("program", "program"))
    path = os.path.join(d, "predict-%s-%d-%d.json"
                        % (safe, os.getpid(), seq))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True, default=repr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def budget_table(reports: List[Dict]) -> str:
    """The pretty budget table ``tpulint --predict`` prints."""
    lines = ["%-14s %-10s %10s %10s %10s %12s %-16s %s"
             % ("entry", "bound", "step_ms", "hbm_MB", "wire_MB",
                "items/s", "calibration", "verdict")]
    for r in reports:
        b = r.get("budget", {})
        basis = r.get("basis", {})
        over = r.get("over_budget") or []
        lines.append(
            "%-14s %-10s %10s %10.2f %10.3f %12s %-16s %s"
            % (r.get("program", "?")[:14], basis.get("bound", "?"),
               ("%.4g" % (1e3 * b["step_time_s"]))
               if b.get("step_time_s") else "-",
               (b.get("peak_hbm_bytes") or 0) / 1e6,
               (b.get("wire_bytes_per_step") or 0) / 1e6,
               ("%.1f" % b["throughput_per_s"])
               if b.get("throughput_per_s") else "-",
               "%s n=%s f=%.2f" % (basis.get("calibration_source", "?"),
                                   basis.get("calibration_n", 0),
                                   basis.get("achievable_fraction", 0.0)),
               ("OVER BUDGET: " + ",".join(over)) if over else "ok"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# conformance: measured vs budget
# ---------------------------------------------------------------------------

def note_budget(program: str, report: Dict) -> None:
    """Remember a program's budget of record (runtime conformance
    compares against it; latest note wins)."""
    with _LOCK:
        _NOTED[program] = report


def noted_budget(program: str) -> Optional[Dict]:
    with _LOCK:
        return _NOTED.get(program)


def _drawdown_sigma(history: List[float]) -> float:
    """benchwatch's drawdown-σ (tools/benchwatch.py) when importable,
    else the same computation inline — the bands must match the gate."""
    try:
        import importlib.util
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        path = os.path.join(root, "tools", "benchwatch.py")
        spec = importlib.util.spec_from_file_location("_mxt_benchwatch",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return float(mod.drawdown_sigma(list(history)))
    except Exception:
        if len(history) < 2:
            return 0.0
        run_max = history[0]
        draws = []
        for v in history[1:]:
            run_max = max(run_max, v)
            draws.append((run_max - v) / run_max if run_max > 0 else 0.0)
        if len(draws) < 2:
            return 0.0
        return statistics.stdev(draws)


def conformance_bands(history: Optional[List[float]] = None,
                      floor: float = CONFORMANCE_FLOOR,
                      sigma_mult: float = SIGMA_MULT) -> Dict:
    """Verdict bands for one metric: DEGRADED past ``max(σ·noise,
    floor)`` in the bad direction, VIOLATED past twice that — the
    benchwatch gate formula applied to prediction drift."""
    noise = _drawdown_sigma(history or [])
    tol = max(sigma_mult * noise, floor)
    return {"degraded_tolerance": round(tol, 4),
            "violated_tolerance": round(2 * tol, 4),
            "noise_sigma": round(noise, 4),
            "basis": "sigma" if sigma_mult * noise > floor else "floor"}


_LOWER_IS_BETTER = {"step_time_s": True, "peak_hbm_bytes": True,
                    "wire_bytes_per_step": True,
                    "throughput_per_s": False,
                    "decode_tokens_per_s": False}


def conformance(budget_report: Dict, measured: Dict,
                history_by_metric: Optional[Dict] = None,
                floor: float = CONFORMANCE_FLOOR,
                sigma_mult: float = SIGMA_MULT) -> Optional[Dict]:
    """Per-metric measured/predicted ratios + verdicts against one
    budget.  ``measured`` maps metric names (budget schema keys, plus
    ``decode_tokens_per_s`` which compares against the throughput
    budget) to measured values; metrics without both sides are
    skipped.  None when nothing is comparable."""
    budget = budget_report.get("budget", budget_report)
    metrics = {}
    worst = "WITHIN"
    for metric, meas in measured.items():
        lower = _LOWER_IS_BETTER.get(metric)
        if lower is None or meas is None:
            continue
        budget_key = "throughput_per_s" \
            if metric == "decode_tokens_per_s" else metric
        pred = budget.get(budget_key)
        if not pred:
            continue
        ratio = float(meas) / float(pred)
        badness = (ratio - 1.0) if lower else (1.0 / max(ratio, 1e-9)
                                               - 1.0)
        bands = conformance_bands((history_by_metric or {}).get(metric),
                                  floor=floor, sigma_mult=sigma_mult)
        if badness <= bands["degraded_tolerance"]:
            verdict = "WITHIN"
        elif badness <= bands["violated_tolerance"]:
            verdict = "DEGRADED"
        else:
            verdict = "VIOLATED"
        if VERDICTS.index(verdict) > VERDICTS.index(worst):
            worst = verdict
        metrics[metric] = {"measured": float(meas),
                           "predicted": float(pred),
                           "ratio": round(ratio, 4),
                           "verdict": verdict, "band": bands}
    if not metrics:
        return None
    return {"verdict": worst, "metrics": metrics,
            "budget_program": budget_report.get("program"),
            "calibration_source": (budget_report.get("basis") or {})
            .get("calibration_source")}


def runtime_conformance(program: str, data: Dict,
                        store: Optional[Dict] = None) -> Optional[Dict]:
    """The attribution-time conformance pass (telemetry/perf.py calls
    this once per attributed program, after the warmup):

    * with a noted pre-flight budget: measured step (telemetry p50),
      measured peak HBM (memory plane) and the compiled program's
      audited wire bytes are all held against what was promised;
    * without one: a self-budget is derived from the report's own
      static analytics × the calibrated fraction, and only step time is
      compared (the other metrics would be compared against
      themselves).

    When the run produced a measured step, the sample also refits the
    calibration store (disable with ``MXNET_TPU_CALIBRATION_REFIT=0``).
    """
    step = data.get("step") or {}
    measured_s = step.get("measured_s")
    if not measured_s:
        return None

    store = load_store() if store is None else store
    budget_rep = noted_budget(program)
    measured: Dict[str, float] = {"step_time_s": float(measured_s)}
    if budget_rep is not None:
        mm = (data.get("memory") or {}).get("measured") or {}
        if mm.get("peak_live_bytes"):
            measured["peak_hbm_bytes"] = float(mm["peak_live_bytes"])
        wire = (data.get("analytic") or {}).get("collective_wire_bytes")
        if wire:
            measured["wire_bytes_per_step"] = float(wire)
    else:
        roof = data.get("roofline") or {}
        device_roof = roof.get("device_roof_s")
        if not device_roof:
            return None
        comp = {"compute": roof.get("compute_s", 0.0),
                "hbm": roof.get("hbm_s", 0.0),
                "collective": roof.get("collective_s", 0.0)}
        bucket = max(comp, key=comp.get)
        kind = ((data.get("topology") or {}).get("device_kind")
                or device_kind())
        cal = achievable_fraction(store, kind, bucket)
        budget_rep = {
            "program": program,
            "budget": {"step_time_s": device_roof / cal["fraction"]},
            "basis": {"bound": bucket,
                      "achievable_fraction": cal["fraction"],
                      "calibration_source": cal["source"],
                      "calibration_n": cal["n"]},
        }
    conf = conformance(budget_rep, measured)
    if conf:
        with _LOCK:
            _LAST_CONFORMANCE[program] = conf
    # the sample refits the store only AFTER the budget was derived —
    # calibrating the budget from the very step it judges would make
    # every verdict read WITHIN by construction
    if os.environ.get("MXNET_TPU_CALIBRATION_REFIT", "1") not in (
            "0", "false", "off"):
        try:
            if fit_from_attribution(store, data) is not None:
                save_store(store)
        except Exception:
            pass
    return conf


def digest_column() -> Optional[Dict]:
    """This rank's worst conformance outcome, compact enough for the
    ~200-byte heartbeat digest: ``{"ratio", "verdict", "metric",
    "program"}`` — the fleet view's per-rank budget column."""
    with _LOCK:
        items = list(_LAST_CONFORMANCE.items())
    worst = None
    for program, conf in items:
        for metric, info in (conf.get("metrics") or {}).items():
            lower = _LOWER_IS_BETTER.get(metric, True)
            badness = (info["ratio"] - 1.0) if lower \
                else (1.0 / max(info["ratio"], 1e-9) - 1.0)
            cand = (VERDICTS.index(info["verdict"]), badness,
                    {"ratio": info["ratio"], "verdict": info["verdict"],
                     "metric": metric, "program": program})
            if worst is None or cand[:2] > worst[:2]:
                worst = cand
    return worst[2] if worst else None


def reset() -> None:
    """Forget noted budgets + conformance outcomes (tests)."""
    with _LOCK:
        _NOTED.clear()
        _LAST_CONFORMANCE.clear()
