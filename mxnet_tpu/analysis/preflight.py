"""Opt-in pre-flight: run graphcheck on the traced program before step 0.

Wireup (``MXNET_TPU_PREFLIGHT=1``):

* ``ShardedTrainer.step`` — the first step traces the exact step program
  and runs every graphcheck pass BEFORE dispatching to devices.
* ``Module.bind`` — the bound executor's fused forward(+backward)
  program is checked the same way.

On ERROR-severity findings the run aborts with
:class:`~mxnet_tpu.analysis.report.PreflightError` (unless
``MXNET_TPU_PREFLIGHT_ACTION=warn``), and ALWAYS writes the report —
JSON next to the checkpoints, exactly where the PR-2 watchdog puts its
post-mortems, so the forensics for "refused to launch" and "hung at
step N" live in one place.

Env knobs:

=====================================  ====================================
``MXNET_TPU_PREFLIGHT``                master switch (``1`` on; default off)
``MXNET_TPU_PREFLIGHT_ACTION``         ``abort`` (default): raise
                                       PreflightError on ERROR findings;
                                       ``warn``: log and continue
``MXNET_TPU_PREFLIGHT_DIR``            report directory (default: the
                                       watchdog/checkpoint dir, else cwd)
``MXNET_TPU_PREFLIGHT_HLO``            ``1``: also compile and dump the
                                       optimized HLO next to the report
                                       (feeds tools/hlo_diff.py
                                       ``--from-graphcheck``; costs one
                                       extra compile)
``MXNET_TPU_PREFLIGHT_REPLICATED_MB``  GC201 size threshold (default 8)
=====================================  ====================================
"""
from __future__ import annotations

import logging
import os

from .report import PreflightError, Report

__all__ = ["enabled", "report_dir", "run_trainer_preflight",
           "run_module_preflight", "write_report"]

_PREFIX = "preflight"
_SEQ = [0]          # per-process report counter: several trainers/modules
                    # in one process must not overwrite each other


def enabled() -> bool:
    return os.environ.get("MXNET_TPU_PREFLIGHT", "0") not in (
        "0", "", "false", "off")


def _action() -> str:
    act = os.environ.get("MXNET_TPU_PREFLIGHT_ACTION", "abort")
    return act if act in ("abort", "warn") else "abort"


def report_dir() -> str:
    explicit = os.environ.get("MXNET_TPU_PREFLIGHT_DIR")
    if explicit:
        return explicit
    from ..resilience import watchdog as _wd
    return (os.environ.get("MXNET_TPU_WATCHDOG_DIR")
            or _wd.default_report_dir()
            or os.getcwd())


def write_report(report: Report, name: str, jaxpr=None,
                 hlo_text: str = None) -> str:
    """Persist the report (+ jaxpr text, + optional HLO) under the report
    dir; returns the JSON path.  Artifact paths are recorded IN the
    report so ``hlo_diff --from-graphcheck`` can find them."""
    d = report_dir()
    os.makedirs(d, exist_ok=True)
    _SEQ[0] += 1
    base = os.path.join(d, "%s-%s-%d-%d" % (_PREFIX, name, os.getpid(),
                                            _SEQ[0]))
    if jaxpr is not None:
        jaxpr_path = base + ".jaxpr.txt"
        with open(jaxpr_path, "w") as f:
            f.write(str(jaxpr))
        report.artifacts["jaxpr"] = jaxpr_path
    if hlo_text is not None:
        hlo_path = base + ".hlo.txt"
        with open(hlo_path, "w") as f:
            f.write(hlo_text)
        report.artifacts["hlo"] = hlo_path
    return report.save(base + ".json")


def _finish(report: Report, name: str, jaxpr=None, hlo_text=None):
    path = write_report(report, name, jaxpr=jaxpr, hlo_text=hlo_text)
    errors = report.errors()
    if errors:
        msg = ("pre-flight found %d ERROR finding(s) in %s "
               "(report: %s):\n%s"
               % (len(errors), report.target, path,
                  "\n".join("  [%s] %s" % (f.rule, f.message)
                            for f in errors)))
        if _action() == "abort":
            raise PreflightError(msg, report)
        logging.error("%s\nMXNET_TPU_PREFLIGHT_ACTION=warn: continuing "
                      "anyway", msg)
    else:
        logging.info("pre-flight clean for %s (%d warnings; report: %s)",
                     report.target, len(report.warnings()), path)
    return path


def run_trainer_preflight(trainer, params, mom, aux, inputs):
    """Check a ShardedTrainer's step program; called by the trainer on its
    first step when enabled.  Raises PreflightError on ERROR findings."""
    from . import graphcheck
    rep, closed = graphcheck.check_trainer(trainer, params, mom, aux,
                                           inputs)
    hlo_text = None
    if os.environ.get("MXNET_TPU_PREFLIGHT_HLO", "0") not in ("0", ""):
        try:
            keys = trainer._keys()
            guard = trainer._guard_arrays()
            compiled = trainer._step.lower(
                params, mom, aux, inputs, keys, guard).compile()
            hlo_text = compiled.as_text()
            # feed the memory plane: the OOM forensics and the GC501
            # refinement both want this program's compiled breakdown
            from ..telemetry import memory as _memory
            _memory.note_program(
                "ShardedTrainer.step(%s)" % (trainer.symbol.name
                                             or "symbol"), compiled)
            from . import costmodel, graphcheck
            breakdown = costmodel.memory_breakdown(compiled)
            if breakdown.get("peak_bytes"):
                rep.extend(graphcheck.check_capacity(
                    breakdown["peak_bytes"], target=rep.target,
                    detail={"basis": "memory_analysis", **breakdown}))
            # GC304: with the optimized HLO in hand, prove the step's
            # collectives have compute to hide behind
            rep.extend(graphcheck.check_overlap(hlo_text,
                                                target=rep.target))
        except Exception:
            logging.exception("pre-flight: HLO dump failed (continuing)")
    return _finish(rep, "trainer", jaxpr=closed, hlo_text=hlo_text)


def run_module_preflight(module):
    """Check a bound Module's head executor program; called from
    Module.bind when enabled."""
    from . import graphcheck
    executor = module._exec_group.execs[0]
    rep, closed = graphcheck.check_executor(executor,
                                            train=module.for_training)
    return _finish(rep, "module", jaxpr=closed)
