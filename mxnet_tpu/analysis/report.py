"""Shared finding/report model for the static-analysis engines.

Both engines (graphcheck: jaxpr-level SPMD/perf lint; srclint: AST-level
host-footgun lint) emit :class:`Finding` records into a :class:`Report`.
A finding is one rule violation: rule id, severity, human message, a
location string (file:line for srclint, a jaxpr path like
``shard_map/scan.body`` for graphcheck), and a fix hint.  Reports render
as JSON (machine: CI gates, ``tools/hlo_diff.py --from-graphcheck``) or
pretty text (human: the ``tools/postmortem.py`` style), and carry enough
provenance (engine, target, artifact paths) to act on after the run.

Severity model — three levels, ordered:

* ``error``   — the program is statically wrong in a way that will hang,
  crash, or silently corrupt training (e.g. a rank-divergent collective
  schedule).  Pre-flight aborts on these.
* ``warning`` — a real hazard that may be intentional (replicated large
  buffer, missing donation, recompile-per-step attr).
* ``info``    — noteworthy but usually benign.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional

__all__ = ["Finding", "Report", "PreflightError", "SEVERITIES",
           "severity_rank"]

SEVERITIES = ("info", "warning", "error")


def severity_rank(sev: str) -> int:
    """Numeric order of a severity name (unknown names rank highest so a
    typo'd severity is never silently ignored by a gate)."""
    try:
        return SEVERITIES.index(sev)
    except ValueError:
        return len(SEVERITIES)


class PreflightError(RuntimeError):
    """Raised when a pre-flight check finds ERROR-severity problems; the
    offending :class:`Report` rides along as ``.report``."""

    def __init__(self, message, report: "Report" = None):
        super().__init__(message)
        self.report = report


class Finding:
    """One rule violation."""

    __slots__ = ("rule", "severity", "message", "location", "fix_hint",
                 "extra")

    def __init__(self, rule: str, severity: str, message: str,
                 location: str = "", fix_hint: str = "",
                 extra: Optional[Dict] = None):
        if severity not in SEVERITIES:
            raise ValueError("severity must be one of %s, got %r"
                             % (SEVERITIES, severity))
        self.rule = rule
        self.severity = severity
        self.message = message
        self.location = location
        self.fix_hint = fix_hint
        self.extra = dict(extra or {})

    def to_dict(self) -> Dict:
        d = {"rule": self.rule, "severity": self.severity,
             "message": self.message, "location": self.location}
        if self.fix_hint:
            d["fix_hint"] = self.fix_hint
        if self.extra:
            d["extra"] = self.extra
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "Finding":
        return cls(d["rule"], d["severity"], d["message"],
                   d.get("location", ""), d.get("fix_hint", ""),
                   d.get("extra"))

    def __repr__(self):
        return "<Finding %s %s @ %s: %s>" % (
            self.rule, self.severity.upper(), self.location or "?",
            self.message)


class Report:
    """A bag of findings from one engine run over one target."""

    def __init__(self, engine: str, target: str = "",
                 findings: Optional[Iterable[Finding]] = None,
                 artifacts: Optional[Dict[str, str]] = None):
        self.engine = engine
        self.target = target
        self.findings: List[Finding] = list(findings or [])
        # paths to things a downstream tool can chew on: the dumped
        # jaxpr/HLO text for hlo_diff, the fixture file for srclint, ...
        self.artifacts: Dict[str, str] = dict(artifacts or {})
        self.time = time.time()

    # -- building ---------------------------------------------------------
    def add(self, rule, severity, message, location="", fix_hint="",
            extra=None):
        self.findings.append(Finding(rule, severity, message, location,
                                     fix_hint, extra))

    def extend(self, other: "Report"):
        self.findings.extend(other.findings)
        self.artifacts.update(other.artifacts)

    # -- querying ---------------------------------------------------------
    def by_severity(self, severity: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    def errors(self) -> List[Finding]:
        return self.by_severity("error")

    def warnings(self) -> List[Finding]:
        return self.by_severity("warning")

    def at_or_above(self, severity: str) -> List[Finding]:
        floor = severity_rank(severity)
        return [f for f in self.findings
                if severity_rank(f.severity) >= floor]

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] = out.get(f.severity, 0) + 1
        return out

    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    # -- rendering --------------------------------------------------------
    def to_dict(self) -> Dict:
        return {
            "kind": "analysis_report",
            "engine": self.engine,
            "target": self.target,
            "time": self.time,
            "counts": self.counts(),
            "findings": [f.to_dict() for f in self.sorted()],
            "artifacts": self.artifacts,
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, default=repr)

    @classmethod
    def from_dict(cls, d: Dict) -> "Report":
        rep = cls(d.get("engine", "?"), d.get("target", ""),
                  [Finding.from_dict(f) for f in d.get("findings", [])],
                  d.get("artifacts"))
        rep.time = d.get("time", rep.time)
        return rep

    @classmethod
    def load(cls, path: str) -> "Report":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str) -> str:
        """Atomic JSON write (same temp+replace discipline as the
        checkpoint container — a preempted pre-flight must not leave a
        truncated report for the next tool to choke on)."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def sorted(self) -> List[Finding]:
        """Findings, most severe first, then by location for stability."""
        return sorted(self.findings,
                      key=lambda f: (-severity_rank(f.severity), f.rule,
                                     f.location))

    def pretty(self, max_findings: int = 0) -> str:
        """Human rendering (tools/postmortem.py style)."""
        lines = []
        rule = "=" * 72
        lines.append(rule)
        lines.append("STATIC ANALYSIS [%s] %s" % (self.engine, self.target))
        lines.append(rule)
        c = self.counts()
        lines.append("findings: %d error / %d warning / %d info"
                     % (c["error"], c["warning"], c["info"]))
        shown = self.sorted()
        if max_findings and len(shown) > max_findings:
            lines.append("(showing %d of %d)" % (max_findings, len(shown)))
            shown = shown[:max_findings]
        for f in shown:
            lines.append("-" * 72)
            lines.append("%-7s %s  %s" % (f.severity.upper(), f.rule,
                                          f.location))
            lines.append("    %s" % f.message)
            if f.fix_hint:
                lines.append("    fix: %s" % f.fix_hint)
        for name, path in sorted(self.artifacts.items()):
            lines.append("artifact %s: %s" % (name, path))
        lines.append("")
        return "\n".join(lines)
