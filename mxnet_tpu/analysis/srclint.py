"""Source linter: AST-level host-footgun scan (pre-flight Engine 2).

Where graphcheck inspects the *traced* program, this engine inspects the
*source* for mistakes that either never reach a trace (they crash or
silently freeze a value at trace time) or that tracing cannot see
(missing watchdog arming).  It is deliberately heuristic and
conservative: a rule only fires on patterns that are near-certainly
wrong, because the repo self-lint (tests/test_analysis.py) requires zero
false positives on the shipped tree.

**Traced-context detection.**  A function is considered traced when it
(a) is decorated with ``jax.jit`` / ``functools.partial(jax.jit, ...)``
/ ``jax.checkpoint`` / the op-registry ``@register``; (b) is passed by
name into a tracing combinator (``jit``, ``shard_map``, ``grad``,
``value_and_grad``, ``vjp``, ``scan``, ``cond``, ``while_loop``,
``vmap``, ``remat``, ``eval_shape``, ``make_jaxpr``, ``pallas_call``,
...); (c) contains collective primitives (``lax.psum`` et al. only make
sense under trace); or (d) is defined inside, or called by name from,
another traced function in the same module (propagated to fixpoint —
host helpers that *run at trace time* inherit the constraint, because
whatever they compute is frozen into the program).

Rule catalog (docs/static-analysis.md):

======  =========================  ========  ===============================
id      name                       severity  what it catches
======  =========================  ========  ===============================
SL101   host-numpy-on-tracer       error     ``np.f(x)`` where ``x`` is a
                                             traced-function parameter —
                                             crashes at trace or silently
                                             constant-folds
SL102   time-in-jit                error     ``time.time()`` etc. inside a
                                             traced function: frozen at
                                             trace, never ticks again
SL103   env-read-in-jit            warning   env reads inside a traced
                                             function: frozen at first
                                             trace, per-step changes lost
SL104   python-rng-in-jit          error     ``random.*`` / ``np.random.*``
                                             inside a traced function: the
                                             same "random" numbers replay
                                             every step
SL105   tracer-leak-to-self        warning   ``self.x = ...`` inside a
                                             traced function: leaks a
                                             tracer out of the trace
SL106   unarmed-collective-entry   warning   library function that builds a
                                             shard_map program but never
                                             arms the hang watchdog around
                                             its execution
SL107   manual-timing-use-spans    info      host-side library function
                                             hand-rolling start/stop
                                             timing (``t0 = time.X(); ...
                                             time.X() - t0``) instead of a
                                             telemetry span — the
                                             measurement is invisible to
                                             the merged trace and the
                                             metrics registry
SL108   sync-iter-in-train-loop    warning   training loop iterating a
                                             synchronous ``DataIter``
                                             (``NDArrayIter``/``CSVIter``/
                                             ...) with no
                                             ``PrefetchingIter`` wrapper:
                                             every batch fetch stalls the
                                             step — the static twin of
                                             the attribution report's
                                             ``bound: input`` verdict
======  =========================  ========  ===============================

**Suppression syntax** (``docs/static-analysis.md``):

* line:      ``x = np.sqrt(p)  # tpulint: disable=SL101``
* function:  the same comment on the ``def`` line covers the body
* file:      a ``# tpulint: disable-file=SL105,SL106`` line anywhere
* ``disable=all`` disables every rule at that scope
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .report import Report

__all__ = ["lint_source", "lint_file", "lint_paths", "RULES"]

RULES = {
    "SL101": ("host-numpy-on-tracer", "error"),
    "SL102": ("time-in-jit", "error"),
    "SL103": ("env-read-in-jit", "warning"),
    "SL104": ("python-rng-in-jit", "error"),
    "SL105": ("tracer-leak-to-self", "warning"),
    "SL106": ("unarmed-collective-entry", "warning"),
    "SL107": ("manual-timing-use-spans", "info"),
    "SL108": ("sync-iter-in-train-loop", "warning"),
}

# SL108: the repo's synchronous iterators (every .next() blocks the
# training loop on the host fetch) vs the wrapper that overlaps them
_SYNC_ITER_CONSTRUCTORS = frozenset({
    "NDArrayIter", "CSVIter", "LibSVMIter", "MNISTIter", "ResizeIter",
    "DataIter",
})
_PREFETCH_WRAPPERS = frozenset({"PrefetchingIter"})
# a loop is a TRAINING loop when its body advances a model: optimizer
# steps or the module train path (plain eval/predict sweeps are exempt —
# their fetch stalls nothing downstream)
_TRAIN_STEP_CALLS = frozenset({"step", "forward_backward", "update"})

# bare wall/monotonic clock reads whose subtraction pattern marks a
# hand-rolled timing measurement (SL107)
_CLOCK_BARE = frozenset({"time.time", "time.monotonic",
                         "time.perf_counter"})

# the instrumentation layer itself legitimately reads clocks
_SL107_EXEMPT_PARTS = ("telemetry",)
_SL107_EXEMPT_FILES = ("profiler.py",)

# combinators whose function-valued arguments get traced (matched on the
# last dotted segment: jax.jit, functools.partial(jax.jit, ...), lax.scan)
_TRACING_CALLS = frozenset({
    "jit", "shard_map", "grad", "value_and_grad", "vjp", "jvp",
    "linearize", "checkpoint", "remat", "vmap", "pmap", "xmap",
    "eval_shape", "make_jaxpr", "scan", "cond", "while_loop", "fori_loop",
    "switch", "associative_scan", "custom_vjp", "custom_jvp", "named_call",
    "pallas_call", "apply_backward_mirror",
})

# decorators that mark a def as traced
_TRACING_DECORATORS = frozenset({"jit", "checkpoint", "remat", "register",
                                 "custom_vjp", "custom_jvp"})

_COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "axis_index", "pvary",
})

_TIME_CALLS = frozenset({"time.time", "time.perf_counter", "time.monotonic",
                         "time.sleep", "time.process_time",
                         "time.perf_counter_ns", "time.time_ns"})

# np attributes that are constants/dtypes, not host computation
_NP_BENIGN = frozenset({
    "dtype", "float16", "float32", "float64", "int8", "int16", "int32",
    "int64", "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
    "complex128", "ndarray", "generic", "integer", "floating", "number",
    "newaxis", "pi", "inf", "e", "nan", "shape",
})

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([\w,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*tpulint:\s*disable-file=([\w,\s]+)")


def _dotted(node) -> str:
    """'jax.lax.psum' for an Attribute/Name chain, '' when not static."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _last_segment(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


class _FnInfo:
    """``traced`` levels: 0 = host-only; 1 = runs AT TRACE TIME (reached
    by call from a traced body — its results are frozen into the program,
    but its parameters are usually static config, not tracers); 2 =
    DIRECTLY traced (jitted / passed to a combinator / collective body —
    its parameters ARE tracers)."""

    __slots__ = ("node", "name", "parent", "traced", "param_names",
                 "calls_watch", "builds_shard_map", "lineno")

    TRACED_HOST = 1
    TRACED_DIRECT = 2

    def __init__(self, node, name, parent):
        self.node = node
        self.name = name
        self.parent = parent           # enclosing _FnInfo or None
        self.traced = 0
        self.param_names: Set[str] = set()
        self.calls_watch = False
        self.builds_shard_map = False
        self.lineno = node.lineno


def _index_functions(tree) -> List[_FnInfo]:
    """Every def/lambda with its enclosing function, in document order."""
    infos: List[_FnInfo] = []

    def walk(node, parent):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FnInfo(child, child.name, parent)
                _fill_params(info, child.args)
                infos.append(info)
                walk(child, info)
            elif isinstance(child, ast.Lambda):
                info = _FnInfo(child, "<lambda>", parent)
                _fill_params(info, child.args)
                infos.append(info)
                walk(child, info)
            else:
                walk(child, parent)

    walk(tree, None)
    return infos


def _fill_params(info: _FnInfo, args: ast.arguments):
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)):
        info.param_names.add(a.arg)
    if args.vararg:
        info.param_names.add(args.vararg.arg)
    if args.kwarg:
        info.param_names.add(args.kwarg.arg)


def _own_body_nodes(fn_node):
    """AST nodes of a function body EXCLUDING nested function bodies (so a
    violation is attributed to the innermost function)."""
    out = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            out.append(child)
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                walk(child)

    walk(fn_node)
    return out


def _has_tracing_decorator(node) -> bool:
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _last_segment(_dotted(target)) in _TRACING_DECORATORS:
            return True
        # functools.partial(jax.jit, ...) / partial(jit, static_argnums=..)
        if isinstance(dec, ast.Call) \
                and _last_segment(_dotted(dec.func)) == "partial":
            for a in dec.args:
                if _last_segment(_dotted(a)) in _TRACING_DECORATORS:
                    return True
    return False


def _mark_traced(infos: List[_FnInfo], tree) -> None:
    by_name: Dict[str, List[_FnInfo]] = {}
    for info in infos:
        by_name.setdefault(info.name, []).append(info)

    # seed DIRECT: decorators, collective bodies, names passed to tracing
    # combinators, inline lambdas handed to combinators
    traced_names: Set[str] = set()
    for info in infos:
        if _has_tracing_decorator(info.node):
            info.traced = _FnInfo.TRACED_DIRECT
        for node in _own_body_nodes(info.node):
            if isinstance(node, ast.Call):
                callee = _dotted(node.func)
                if _last_segment(callee) in _COLLECTIVE_CALLS \
                        and ("lax" in callee or "jax" in callee
                             or callee in _COLLECTIVE_CALLS):
                    info.traced = _FnInfo.TRACED_DIRECT
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_segment(_dotted(node.func)) not in _TRACING_CALLS:
            continue
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Name):
                traced_names.add(a.id)
            elif isinstance(a, ast.Lambda):
                for info in infos:
                    if info.node is a:
                        info.traced = _FnInfo.TRACED_DIRECT
    for name in traced_names:
        for info in by_name.get(name, []):
            info.traced = _FnInfo.TRACED_DIRECT

    # propagate: nested defs of DIRECT fns see tracers too; same-module
    # functions CALLED from any traced body run at trace time (HOST level
    # — their results are frozen, but their params are usually static)
    changed = True
    while changed:
        changed = False
        for info in infos:
            if info.parent is not None \
                    and info.parent.traced == _FnInfo.TRACED_DIRECT \
                    and info.traced < _FnInfo.TRACED_DIRECT:
                info.traced = _FnInfo.TRACED_DIRECT
                changed = True
        for info in infos:
            if not info.traced:
                continue
            for node in _own_body_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = _dotted(node.func)
                if "." in callee or not callee:
                    continue               # cross-module / dynamic: skip
                for target in by_name.get(callee, []):
                    if not target.traced:
                        target.traced = _FnInfo.TRACED_HOST
                        changed = True


class _Suppressions:
    def __init__(self, source: str):
        self.lines = source.splitlines()
        self.file_wide: Set[str] = set()
        for line in self.lines:
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self.file_wide |= {t.strip() for t in m.group(1).split(",")}

    def _line_set(self, lineno: int) -> Set[str]:
        if 1 <= lineno <= len(self.lines):
            m = _SUPPRESS_RE.search(self.lines[lineno - 1])
            if m:
                return {t.strip() for t in m.group(1).split(",")}
        return set()

    def active(self, rule: str, lineno: int,
               fn: Optional[_FnInfo]) -> bool:
        for scope in (self.file_wide, self._line_set(lineno),
                      self._line_set(fn.lineno) if fn else set()):
            if "all" in scope or rule in scope:
                return True
        return False


def _enclosing_params(fn: _FnInfo) -> Set[str]:
    """Parameter names of a DIRECTLY-traced fn and every directly-traced
    enclosing fn — values flowing in from any of them are (potentially)
    tracers.  Host-level (trace-time helper) params are excluded: they
    usually carry static config, not tracers."""
    names: Set[str] = set()
    cur = fn
    while cur is not None and cur.traced == _FnInfo.TRACED_DIRECT:
        names |= cur.param_names
        cur = cur.parent
    return names


def _call_arg_names(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, ast.Name):
            out.add(a.id)
        elif isinstance(a, ast.Starred) and isinstance(a.value, ast.Name):
            out.add(a.value.id)
    return out


def lint_source(source: str, filename: str = "<string>",
                in_library: bool = False) -> Report:
    """Lint one python source text.  ``in_library``: apply the
    library-only rules (SL106) — True for files under ``mxnet_tpu/``."""
    rep = Report("srclint", filename)
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        rep.add("SL000", "error", "file does not parse: %s" % e,
                location="%s:%s" % (filename, e.lineno or 0))
        return rep
    sup = _Suppressions(source)
    infos = _index_functions(tree)
    _mark_traced(infos, tree)

    def add(rule, lineno, fn, message, fix_hint=""):
        if sup.active(rule, lineno, fn):
            return
        rep.add(rule, RULES[rule][1], message,
                location="%s:%d" % (filename, lineno), fix_hint=fix_hint,
                extra={"function": fn.name if fn else ""})

    for fn in infos:
        body = _own_body_nodes(fn.node)
        for node in body:
            if isinstance(node, ast.Call):
                callee = _dotted(node.func)
                if _last_segment(callee) == "watch":
                    fn.calls_watch = True
                if _last_segment(callee) == "shard_map":
                    fn.builds_shard_map = True
        if not fn.traced:
            continue
        tracer_names = _enclosing_params(fn)
        for node in body:
            if isinstance(node, ast.Call):
                callee = _dotted(node.func)
                root = callee.split(".", 1)[0]
                last = _last_segment(callee)
                if root in ("np", "numpy") and last not in _NP_BENIGN \
                        and not callee.startswith(("np.random.",
                                                   "numpy.random.")) \
                        and (_call_arg_names(node) & tracer_names):
                    add("SL101", node.lineno, fn,
                        "host numpy call %s() on traced value(s) %s inside "
                        "traced function %r: crashes at trace time or "
                        "silently freezes the value into the program"
                        % (callee, sorted(_call_arg_names(node)
                                          & tracer_names), fn.name),
                        "use jnp.%s (stays in the traced program)" % last)
                if callee in _TIME_CALLS:
                    add("SL102", node.lineno, fn,
                        "%s() inside traced function %r is evaluated ONCE "
                        "at trace time and frozen into the program"
                        % (callee, fn.name),
                        "move timing to the host loop around the jitted "
                        "call")
                if callee == "os.getenv" or callee == "os.environ.get":
                    add("SL103", node.lineno, fn,
                        "environment read inside traced function %r is "
                        "frozen at first trace; later changes are "
                        "silently ignored" % fn.name,
                        "read the env var at module import or pass the "
                        "value in as an argument")
                if (callee.startswith("random.")
                        or callee.startswith(("np.random.",
                                              "numpy.random."))):
                    add("SL104", node.lineno, fn,
                        "host RNG call %s() inside traced function %r "
                        "produces the SAME \"random\" numbers on every "
                        "call of the compiled program" % (callee, fn.name),
                        "thread a jax.random key in (needs_rng ops get "
                        "one injected)")
            elif isinstance(node, ast.Subscript):
                if _dotted(node.value) == "os.environ":
                    add("SL103", node.lineno, fn,
                        "os.environ[...] inside traced function %r is "
                        "frozen at first trace" % fn.name,
                        "read the env var at module import or pass the "
                        "value in as an argument")
            elif isinstance(node, (ast.Assign, ast.AugAssign)) \
                    and fn.traced == _FnInfo.TRACED_DIRECT:
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        add("SL105", node.lineno, fn,
                            "assignment to self.%s inside traced function "
                            "%r stores a tracer on the object: it escapes "
                            "the trace and is dead (or poison) by the "
                            "next call" % (t.attr, fn.name),
                            "return the value from the traced function "
                            "and store it on the host side")

    if in_library and not _sl107_exempt(filename):
        for fn in infos:
            if fn.traced:
                continue     # traced timing is SL102's (error) territory
            lineno = _manual_timing_site(fn)
            if lineno is None or sup.active("SL107", lineno, fn):
                continue
            rep.add("SL107", RULES["SL107"][1],
                    "host function %r hand-rolls a start/stop timing "
                    "measurement; it never reaches the merged trace or "
                    "the metrics registry" % fn.name,
                    location="%s:%d" % (filename, lineno),
                    fix_hint="wrap the region in telemetry.span(name, "
                             "metric=...) — one measurement feeds the "
                             "trace, histograms, and post-mortems",
                    extra={"function": fn.name})

    # SL108: synchronous-iterator training loops (all files — examples
    # are exactly where the pattern ships).  Module-level scripts and
    # host functions both scanned; eval/predict sweeps never match
    # because their loop bodies advance no optimizer.
    scopes = [(None, _own_body_nodes(tree))]
    scopes += [(fn, _own_body_nodes(fn.node)) for fn in infos
               if not fn.traced]
    for fn, body in scopes:
        sync_vars: Dict[str, str] = {}       # var -> constructor name
        wrapped: Set[str] = set()
        for node in body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                ctor = _last_segment(_dotted(node.value.func))
                if ctor in _SYNC_ITER_CONSTRUCTORS:
                    sync_vars[node.targets[0].id] = ctor
                elif ctor in _PREFETCH_WRAPPERS:
                    for a in ast.walk(node.value):
                        if isinstance(a, ast.Name):
                            wrapped.add(a.id)
        if not sync_vars:
            continue
        for node in body:
            if not (isinstance(node, ast.For)
                    and isinstance(node.iter, ast.Name)
                    and node.iter.id in sync_vars
                    and node.iter.id not in wrapped):
                continue
            if not any(isinstance(sub, ast.Call)
                       and _last_segment(_dotted(sub.func))
                       in _TRAIN_STEP_CALLS
                       for sub in ast.walk(node)):
                continue
            add("SL108", node.lineno, fn,
                "training loop iterates synchronous %s %r directly: "
                "every batch fetch blocks the step (the runtime twin is "
                "the attribution report's 'bound: input' verdict)"
                % (sync_vars[node.iter.id], node.iter.id),
                "wrap it: it = PrefetchingIter(it) overlaps the fetch "
                "with step compute")

    if in_library:
        for fn in infos:
            if fn.traced or not fn.builds_shard_map or fn.calls_watch:
                continue
            if sup.active("SL106", fn.lineno, fn):
                continue
            rep.add("SL106", RULES["SL106"][1],
                    "%r builds a shard_map program but never arms the "
                    "hang watchdog around its execution: a dead peer "
                    "blocks here with zero diagnostics" % fn.name,
                    location="%s:%d" % (filename, fn.lineno),
                    fix_hint="wrap the execution in resilience.watchdog."
                             "watch(tag, kind='collective') like "
                             "parallel/ring.py does",
                    extra={"function": fn.name})
    return rep


def _sl107_exempt(filename: str) -> bool:
    parts = os.path.normpath(filename).split(os.sep)
    return (any(p in _SL107_EXEMPT_PARTS for p in parts)
            or (parts and parts[-1] in _SL107_EXEMPT_FILES))


def _manual_timing_site(fn: _FnInfo) -> Optional[int]:
    """Line of the first elapsed-time subtraction in ``fn``'s own body:
    ``end_or_clockcall - var`` where ``var`` was assigned from a BARE
    clock read in the same function.  Deadline arithmetic
    (``time.monotonic() + budget``) never matches, because the stored
    name is not a bare clock read."""
    body = _own_body_nodes(fn.node)
    timevars: Set[str] = set()
    for node in body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _dotted(node.value.func) in _CLOCK_BARE:
            timevars.add(node.targets[0].id)
    if not timevars:
        return None

    def time_sourced(expr):
        if isinstance(expr, ast.Call) and _dotted(expr.func) in _CLOCK_BARE:
            return True
        return isinstance(expr, ast.Name) and expr.id in timevars

    for node in body:
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                and isinstance(node.right, ast.Name) \
                and node.right.id in timevars \
                and time_sourced(node.left):
            return node.lineno
    return None


def lint_file(path: str, in_library: Optional[bool] = None) -> Report:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    if in_library is None:
        in_library = "mxnet_tpu" in os.path.normpath(path).split(os.sep)
    return lint_source(source, filename=path, in_library=in_library)


def _iter_py_files(paths: Iterable[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def lint_paths(paths: Iterable[str]) -> Report:
    """Lint every ``.py`` file under ``paths`` into one combined report."""
    rep = Report("srclint", ", ".join(paths))
    for path in _iter_py_files(paths):
        rep.extend(lint_file(path))
    return rep
