"""Static analysis: pre-flight program checks + repo footgun lint.

Two engines over one finding/report model (``report.py``):

* :mod:`~mxnet_tpu.analysis.graphcheck` — jaxpr-level SPMD/perf lint:
  trace any jittable program and statically reject mismatched collective
  schedules, replicated-memory and donation hazards, dtype/precision
  mistakes, and recompile-per-step attrs BEFORE anything runs on a pod.
* :mod:`~mxnet_tpu.analysis.srclint` — AST-level scan of the source tree
  for host-side footguns inside traced functions (host numpy / clocks /
  env reads / Python RNG / tracer leaks) and unarmed collective entry
  points.

Plus :mod:`~mxnet_tpu.analysis.costmodel`: the analytic FLOPs / byte /
collective / roofline model over optimized HLO that the performance
attribution plane (:mod:`mxnet_tpu.telemetry.perf`) is built on, and
:mod:`~mxnet_tpu.analysis.predict`: the calibrated prediction layer on
top of it — persisted achievable-fraction calibration, pre-flight
step-time/HBM/wire/throughput budgets (``tpulint --predict``), and the
runtime conformance verdicts the attribution reports carry.

Wired into ``ShardedTrainer.step`` / ``Module.bind`` as an opt-in
pre-flight (``MXNET_TPU_PREFLIGHT=1``, see
:mod:`~mxnet_tpu.analysis.preflight`), into CI via
``tests/test_analysis.py``, and onto the command line as
``tools/tpulint.py``.  Rule catalog: ``docs/static-analysis.md``.
"""
from __future__ import annotations

from .report import Finding, PreflightError, Report, SEVERITIES
from . import costmodel, graphcheck, predict, preflight, srclint

__all__ = ["Finding", "Report", "PreflightError", "SEVERITIES",
           "costmodel", "graphcheck", "predict", "preflight", "srclint"]
