"""Engine controls (reference python/mxnet/engine.py).

The reference's bulk execution bundles small engine ops to cut dispatch
overhead (MXEngineSetBulkSize).  In this stack the XLA compiler already
fuses whole traced programs, and eager ops go through cached jitted
closures — there is no engine queue to bundle.  The API is kept so
`with mx.engine.bulk(n):` scopes in ported scripts run unchanged; the
size is recorded (visible via current_bulk_size) and is advisory.
"""
__all__ = ["set_bulk_size", "bulk", "current_bulk_size"]

_bulk_size = 15   # the reference default (MXNET_ENGINE_BULK_SIZE)


def set_bulk_size(size):
    """Record the bulk-size hint; returns the previous value (reference
    engine.py:26).  Advisory here: XLA fusion replaces engine bulking."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


def current_bulk_size():
    return _bulk_size


class _BulkScope:
    def __init__(self, size):
        self._size = size
        self._old = None

    def __enter__(self):
        self._old = set_bulk_size(self._size)
        return self

    def __exit__(self, *a):
        set_bulk_size(self._old)


def bulk(size):
    """Scope form: `with mx.engine.bulk(16): ...` (reference engine.py:63)."""
    return _BulkScope(size)
