"""Unified GSPMD placement over ONE named-axis mesh.

This module is the single source of truth for how tensors are placed on
the mesh (the TensorFlow-system-paper "placement layer", PAPERS.md): the
``__shard__`` annotation grammar, the default tensor-parallel recipe, the
ZeRO state-sharding rule, and the batch-input specs all live here, so
every axis — dp/tp/pp/sp/ep or any user-named axis — resolves through
the same code path and therefore composes.  Consumers:

* ``parallel.trainer.ShardedTrainer`` — params, optimizer state,
  activations and batch inputs (jit/GSPMD inserts the collectives);
* ``executor.GraphProgram`` — ``__shard__`` on *op* nodes becomes a
  ``with_sharding_constraint`` on the op's outputs (activation
  annotations), via :mod:`mxnet_tpu.placement`;
* ``parallel.ring`` / ``parallel.moe`` / ``parallel.pipeline`` — the
  retained ``shard_map`` kernels (ring attention, MoE dispatch, the
  GPipe tick schedule: the three programs the partitioner cannot
  produce) embed in the SAME mesh, so their manual axis coexists with
  the GSPMD-managed ones.

The ``__shard__`` grammar (Symbol attr, per tensor): a comma list of
mesh-axis names or ``*`` per tensor dim, e.g. ``"tp,*"`` shards dim 0
over ``tp``; trailing dims default to ``*``.  Unknown axis names raise;
a named dim that does not divide by its axis extent silently downgrades
to replicated (the annotation is a layout hint, not a shape contract).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["as_mesh", "resolve_spec", "param_sharding", "state_sharding",
           "zero_shard_dim", "batch_sharding", "replicated", "constrain",
           "constrain_outputs"]


def as_mesh(mesh_or_spec) -> Mesh:
    """Accept a jax Mesh or a :class:`~mxnet_tpu.parallel.mesh.MeshSpec`
    everywhere a mesh is needed — kernels and helpers embed in whichever
    the caller holds."""
    return getattr(mesh_or_spec, "mesh", mesh_or_spec)


def resolve_spec(ann: str, shape, mesh: Mesh, name: str = "") -> P:
    """``__shard__`` annotation → PartitionSpec over ``mesh``.

    Raises on arity overflow or unknown axis names (annotation bugs must
    be loud — the graphcheck philosophy); downgrades non-divisible named
    dims to replicated."""
    dims = [None if d.strip() in ("*", "None", "") else d.strip()
            for d in str(ann).split(",")]
    if len(dims) > len(shape):
        raise ValueError(
            "__shard__=%r on %s names %d dims but the tensor has %d"
            % (ann, name or "<tensor>", len(dims), len(shape)))
    unknown = [d for d in dims if d is not None and d not in mesh.axis_names]
    if unknown:
        raise ValueError(
            "__shard__=%r on %s names mesh axes %s not in mesh %s"
            % (ann, name or "<tensor>", unknown, tuple(mesh.axis_names)))
    dims += [None] * (len(shape) - len(dims))
    dims = [d if (d is not None and shape[i] % mesh.shape[d] == 0)
            else None for i, d in enumerate(dims)]
    return P(*dims)


def param_sharding(name: str, shape, mesh: Mesh,
                   tp_axis: Optional[str] = None,
                   ann: Optional[str] = None) -> NamedSharding:
    """Placement for one parameter.

    Explicit ``__shard__`` wins and may name ANY mesh axis.  Otherwise,
    when a tensor-parallel axis is active, the default recipe (SURVEY
    §2.3) shards the output channels of FC/Convolution weights and the
    vocab dim of embeddings over ``tp_axis``; XLA propagates activation
    shardings and inserts the collectives.  Everything else replicates
    (over every axis — unused axes mean replication, which is how a
    pp/ep axis coexists with dp/tp parameters)."""
    if ann is not None:
        return NamedSharding(mesh, resolve_spec(ann, shape, mesh, name))
    if tp_axis is None or mesh.shape.get(tp_axis, 1) <= 1:
        return NamedSharding(mesh, P())
    size = mesh.shape[tp_axis]
    if name.endswith("_weight") and len(shape) in (2, 4) \
            and shape[0] % size == 0 and shape[0] >= size:
        # FC (out, in) / Conv (out, in, kh, kw) / Embedding (vocab, dim):
        # shard dim 0 (output channels / vocab rows) over tp
        return NamedSharding(
            mesh, P(*([tp_axis] + [None] * (len(shape) - 1))))
    return NamedSharding(mesh, P())


def zero_shard_dim(shape, taken, size: int) -> Optional[int]:
    """The dim the ZeRO state shard rides on: the LARGEST free dim that
    divides by the dp extent.  Largest — not first — because an exact
    division of the biggest dim keeps per-shard minor dims fat: sharding
    a conv kernel's tiny kh/kw (the old first-fit choice on
    (out, in, kh, kw) state) leaves shards that strand memory in the
    (8, 128) tile padding and serialize the reduce-scatter on a
    few-element dim.  Ties break to the earliest dim (deterministic
    layouts across processes)."""
    best = None
    for i, d in enumerate(shape):
        if taken[i] is not None:
            continue
        if d % size == 0 and d >= size:
            if best is None or d > shape[best]:
                best = i
    return best


def state_sharding(base: NamedSharding, shape, mesh: Mesh,
                   dp_axis: Optional[str]) -> NamedSharding:
    """Placement for optimizer state (and the ZeRO grad/update view of
    its parameter): the parameter's own sharding plus the dp axis over
    :func:`zero_shard_dim`, so per-chip optimizer bytes — and, with the
    sharded weight update, per-chip update FLOPs — scale as 1/dp."""
    size = mesh.shape.get(dp_axis, 1) if dp_axis else 1
    if size <= 1:
        return base
    dims = list(base.spec) + [None] * (len(shape) - len(base.spec))
    i = zero_shard_dim(shape, dims, size)
    if i is not None:
        dims[i] = dp_axis
    return NamedSharding(mesh, P(*dims))


def batch_sharding(mesh: Mesh, dp_axis: Optional[str],
                   accum: int = 1) -> NamedSharding:
    """Input sharding for one batch tensor: dp over dim 0, or — with
    gradient accumulation — dp over dim 1 under the unsharded micro dim
    the in-jit scan walks."""
    if accum > 1:
        return NamedSharding(mesh, P(None, dp_axis))
    return NamedSharding(mesh, P(dp_axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def constrain(x, sharding: Optional[NamedSharding]):
    """``with_sharding_constraint`` that tolerates a None sharding (the
    no-annotation case) so call sites stay branch-free."""
    if sharding is None:
        return x
    return jax.lax.with_sharding_constraint(x, sharding)


def constrain_outputs(outs: Tuple, ann: str, mesh: Mesh, name: str = ""):
    """Activation annotation: apply a ``__shard__`` constraint to every
    op output it fits (inexact dtype, enough dims for the annotation).
    Outputs the grammar cannot describe pass through untouched — one op
    may emit both the annotated activation and bookkeeping scalars."""
    n_dims = len([d for d in str(ann).split(",")])
    fixed = []
    for o in outs:
        shape = getattr(o, "shape", None)
        if shape is not None and len(shape) >= n_dims:
            try:
                o = constrain(o, NamedSharding(
                    mesh, resolve_spec(ann, shape, mesh, name)))
            except ValueError:
                raise
        fixed.append(o)
    return tuple(fixed)
