"""Ring-attention sequence/context parallelism.

Beyond-reference capability (SURVEY.md §5.7: the reference predates
attention; long-context parallelism here is new work designed for ICI).

Implementation: q/k/v are sharded along the sequence axis over the 'sp'
mesh axis.  Each device holds one sequence block; k/v blocks rotate around
the ring via lax.ppermute while each device accumulates its queries'
attention over every block with numerically-stable online softmax (the
flash/blockwise formulation) — compute overlaps the ICI transfer, HBM
never holds the full (T, T) score matrix, and sequence length scales
linearly with the number of devices.

Public API:
  ring_attention(q, k, v, mesh, axis='sp', causal=False, scale=None)
    q/k/v: (B, T, H, D) global arrays (host or sharded); returns same shape.
  local_ring_attention_fn(...)  — the shard_map'd function for embedding in
    larger sharded programs (e.g. a transformer train step).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:   # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:   # 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map

__all__ = ["ring_attention", "local_ring_attention_fn"]


def _block_attn(q, k, v, mask, scale):
    """One (Tq, Tk) block: returns (unnormalised out, row max, row sumexp)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                     # (B,H,Tq); -inf if all masked
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)                     # (B,H,Tq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def local_ring_attention_fn(axis_name: str, causal: bool, scale: float,
                            num_devices: int):
    """Returns fn(q_blk, k_blk, v_blk) for use inside shard_map over
    `axis_name`; blocks are the per-device sequence shards."""

    def fn(q, k, v):
        my_idx = jax.lax.axis_index(axis_name)
        B, Tq, H, D = q.shape
        Tk = k.shape[1]
        perm = [(j, (j + 1) % num_devices) for j in range(num_devices)]

        def block(i, k_blk, v_blk):
            # which global block do we hold? blocks rotate j -> j+1 each
            # step, so at step i device j holds block (j - i) mod n
            blk_idx = (my_idx - i) % num_devices
            if causal:
                q_pos = my_idx * Tq + jnp.arange(Tq)
                k_pos = blk_idx * Tk + jnp.arange(Tk)
                mask = q_pos[:, None] >= k_pos[None, :]
                mask = mask[None, None]  # (1,1,Tq,Tk)
            else:
                mask = None
            return _block_attn(q, k_blk, v_blk, mask, scale)

        def merge(acc, blk):
            # online softmax merge; -inf maxima (fully-masked so far)
            # guarded
            o_acc, m_acc, l_acc = acc
            o, m, l = blk
            new_m = jnp.maximum(m_acc, m)
            new_m_safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
            alpha = jnp.where(jnp.isfinite(m_acc),
                              jnp.exp(m_acc - new_m_safe), 0.0)
            beta = jnp.where(jnp.isfinite(m),
                             jnp.exp(m - new_m_safe), 0.0)
            l_new = l_acc * alpha + l * beta
            o_new = o_acc * alpha[..., None].swapaxes(1, 2) + \
                o * beta[..., None].swapaxes(1, 2)
            return (o_new, new_m, l_new)

        def step(carry, i):
            k_blk, v_blk, o_acc, m_acc, l_acc = carry
            # double-buffered ring step: block i+1's rotation is issued
            # BEFORE block i's attention, and neither depends on the
            # other — the ICI hop flies while the MXU works (the static
            # overlap instrument proves the schedulability; an async
            # backend realizes it as -start/compute/-done)
            k_next = jax.lax.ppermute(k_blk, axis_name, perm)
            v_next = jax.lax.ppermute(v_blk, axis_name, perm)
            o_new, new_m, l_new = merge((o_acc, m_acc, l_acc),
                                        block(i, k_blk, v_blk))
            return (k_next, v_next, o_new, new_m, l_new), None

        # derive initial accumulators from q so they carry the same
        # shard_map varying axes (and dtype) as the loop outputs
        o0 = jnp.zeros_like(q)
        m0 = jnp.swapaxes(q[..., 0] * 0 - jnp.inf, 1, 2)   # (B,H,Tq)
        l0 = jnp.swapaxes(q[..., 0] * 0, 1, 2)
        (k, v, o, m, l), _ = jax.lax.scan(
            step, (k, v, o0, m0, l0), jnp.arange(num_devices - 1))
        # the LAST block needs no rotation: the old n-step loop's final
        # ppermute only carried k/v home to be discarded — 1/n of the
        # ring's wire bytes for nothing (and n=1 paid a pointless
        # self-permute)
        o, m, l = merge((o, m, l), block(num_devices - 1, k, v))
        l_t = jnp.swapaxes(l, 1, 2)[..., None]   # (B,Tq,H,1)
        return o / jnp.maximum(l_t, 1e-20)

    return fn


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, scale: Optional[float] = None):
    """Multi-device attention over sequence-sharded q/k/v.

    q/k/v: (B, T, H, D); T must divide by mesh.shape[axis].

    ``mesh`` may be a Mesh or MeshSpec and may carry OTHER axes beyond
    ``axis`` (the unified dp×tp×…×sp mesh): the shard_map is manual only
    over the names its specs mention, so this kernel — retained
    hand-written because the blockwise online-softmax ring schedule
    beats anything the partitioner derives — embeds in the same mesh as
    the GSPMD-managed axes and composes with them."""
    from .placement import as_mesh
    mesh = as_mesh(mesh)
    n = mesh.shape[axis]
    if scale is None:
        scale = float(1.0 / np.sqrt(q.shape[-1]))
    fn = local_ring_attention_fn(axis, causal, scale, n)
    spec = P(None, axis, None, None)
    # pre-pvary jax (< 0.6) cannot prove the ring loop carry's replication
    compat = {} if hasattr(jax.lax, "pvary") else {"check_rep": False}
    mapped = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, **compat)
    sharding = NamedSharding(mesh, spec)
    from .. import telemetry as _tel
    from ..resilience import watchdog as _wd
    from .audit import record_collective
    # k/v blocks each make n-1 ppermute hops around the ring
    kv_bytes = int(getattr(k, "nbytes", 0) + getattr(v, "nbytes", 0))
    from ..telemetry import memory as _memory
    with _tel.span("collective/ring_attention", cat="collective",
                   metric="parallel.collective_seconds",
                   kind="collective-permute", bytes=kv_bytes), \
            _wd.watch("parallel.ring_attention", kind="collective"), \
            _memory.oom_guard("parallel.ring_attention",
                              program="ring_attention"):
        q = jax.device_put(q, sharding)
        k = jax.device_put(k, sharding)
        v = jax.device_put(v, sharding)
        _memory.tag((q, k, v), "activations", label="ring_attention.qkv")
        out = jax.jit(mapped)(q, k, v)
    record_collective("collective-permute", "parallel.ring_attention",
                      bytes=kv_bytes)
    from ..telemetry import perf as _perf
    _perf.maybe_attribute_fn(mapped, (q, k, v), "ring_attention",
                             n_devices=n, mesh=mesh)
    return out


def reference_attention(q, k, v, causal=False, scale=None):
    """Single-device reference for testing."""
    if scale is None:
        scale = float(1.0 / np.sqrt(q.shape[-1]))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
