"""Device-mesh construction and sharding helpers.

The scaling-book recipe: pick a mesh (axes named for the parallelism kind),
annotate shardings on program inputs/outputs, let XLA insert the
collectives, profile, iterate.  Axis conventions used across mxnet_tpu:

  'dp' — data parallel (batch dim)       → psum(grads) rides ICI
  'tp' — tensor parallel (hidden dims)   → all_gather/reduce_scatter
  'pp' — pipeline stages                 → ppermute
  'sp' — sequence/context parallel       → ring collectives (ring.py)
  'ep' — expert parallel (MoE)           → all_to_all
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["MeshSpec", "make_mesh", "data_parallel_mesh", "current_mesh",
           "set_current_mesh", "shard_batch", "replicate", "P",
           "describe_devices"]


class MeshSpec:
    """A mesh plus the axis layout used by the sharded trainer."""

    def __init__(self, mesh: Mesh, dp_axis="dp", tp_axis=None, pp_axis=None,
                 sp_axis=None, ep_axis=None):
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.pp_axis = pp_axis
        self.sp_axis = sp_axis
        self.ep_axis = ep_axis

    @property
    def dp_size(self):
        return self.mesh.shape[self.dp_axis] if self.dp_axis else 1

    def batch_sharding(self):
        return NamedSharding(self.mesh, P(self.dp_axis))

    def replicated(self):
        return NamedSharding(self.mesh, P())


_state = threading.local()


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build a Mesh over the (global) device list, ICI-contiguous order."""
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError("mesh of %d devices requested, %d available"
                         % (n, len(devices)))
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None) -> MeshSpec:
    devices = jax.devices()
    n = num_devices or len(devices)
    return MeshSpec(make_mesh((n,), ("dp",)))


def current_mesh() -> Optional[MeshSpec]:
    return getattr(_state, "mesh", None)


def set_current_mesh(spec: Optional[MeshSpec]):
    _state.mesh = spec


def shard_batch(x, spec: MeshSpec):
    """Place a host batch onto the mesh, sharded along dp."""
    return jax.device_put(x, spec.batch_sharding())


def replicate(x, spec: MeshSpec):
    return jax.device_put(x, spec.replicated())


def describe_devices() -> dict:
    """Topology snapshot for diagnostics (the watchdog post-mortem):
    process rank/count, per-device platform/id/process, and the current
    mesh layout if one is active.  Never raises — each field degrades to
    an error string, because this runs while the program may be wedged."""
    out = {}
    try:
        out["process_index"] = jax.process_index()
        out["process_count"] = jax.process_count()
    except Exception as e:
        out["process"] = repr(e)
    try:
        out["devices"] = [
            {"id": d.id, "platform": d.platform,
             "process_index": d.process_index, "kind": str(d.device_kind)}
            for d in jax.devices()]
    except Exception as e:
        out["devices"] = repr(e)
    try:
        spec = current_mesh()
        if spec is not None:
            out["mesh"] = {"shape": dict(spec.mesh.shape),
                           "axes": list(spec.mesh.axis_names)}
    except Exception as e:
        out["mesh"] = repr(e)
    return out
