"""Device-mesh construction and sharding helpers.

The scaling-book recipe: pick a mesh (axes named for the parallelism kind),
annotate shardings on program inputs/outputs, let XLA insert the
collectives, profile, iterate.  Axis conventions used across mxnet_tpu:

  'dp' — data parallel (batch dim)       → psum(grads) rides ICI
  'tp' — tensor parallel (hidden dims)   → all_gather/reduce_scatter
  'pp' — pipeline stages                 → ppermute
  'sp' — sequence/context parallel       → ring collectives (ring.py)
  'ep' — expert parallel (MoE)           → all_to_all
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["MeshSpec", "make_mesh", "data_parallel_mesh", "reform_mesh",
           "current_mesh", "set_current_mesh", "shard_batch", "replicate",
           "P", "describe_devices"]

# mesh-axis naming conventions — MeshSpec.build infers axis ROLES from
# these names, so a 3-axis ("dp","tp","pp") mesh wires itself into the
# trainer (dp/tp), the pipeline (pp) and the elastic re-layout (dp
# absorbs world-size changes) with no extra configuration
_ROLE_AXES = ("dp", "tp", "pp", "sp", "ep")


class MeshSpec:
    """ONE named-axis mesh plus the axis-role layout every parallel
    subsystem shares.  Axis dims are arbitrary — ``build`` accepts any
    ``{name: size}`` layout (``dp×tp×pp``, ``dp×tp×ep``, …) and GSPMD
    composes them: params/state/activations carry ``NamedSharding``
    annotations (parallel/placement.py) and an axis a tensor does not
    name simply replicates over it.

    ``generation`` is the elastic-training incarnation counter: every
    coordinated resize (resilience/elastic.py) re-forms the mesh over
    the surviving device set and bumps it, so telemetry digests and the
    fleet view can tell a live row from a pre-resize ghost."""

    def __init__(self, mesh: Mesh, dp_axis="dp", tp_axis=None, pp_axis=None,
                 sp_axis=None, ep_axis=None, generation=0):
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.pp_axis = pp_axis
        self.sp_axis = sp_axis
        self.ep_axis = ep_axis
        self.generation = int(generation)

    @classmethod
    def build(cls, axes, devices=None, generation=0) -> "MeshSpec":
        """One unified mesh from an ``{axis_name: size}`` mapping (or a
        ``(name, size)`` sequence — insertion order is the device-major
        order, outermost first).  Conventionally-named axes (dp/tp/pp/
        sp/ep) are wired to their roles; other names are carried as
        plain mesh axes reachable via ``__shard__`` annotations."""
        items = list(axes.items()) if isinstance(axes, dict) else \
            [(str(n), int(s)) for n, s in axes]
        names = [n for n, _ in items]
        if len(set(names)) != len(names):
            raise ValueError("duplicate mesh axis names: %r" % (names,))
        mesh = make_mesh([s for _, s in items], names, devices=devices)
        roles = {a + "_axis": (a if a in names else None)
                 for a in _ROLE_AXES}
        return cls(mesh, generation=generation, **roles)

    def axis_size(self, name) -> int:
        return int(self.mesh.shape.get(name, 1)) if name else 1

    @property
    def dp_size(self):
        return self.axis_size(self.dp_axis)

    @property
    def model_axes(self):
        """Active (size > 1) non-dp role axes — what GC201 replication
        warnings and the per-axis collective audit key on."""
        return tuple(a for a in (self.tp_axis, self.pp_axis, self.sp_axis,
                                 self.ep_axis)
                     if a and self.axis_size(a) > 1)

    def batch_sharding(self):
        return NamedSharding(self.mesh, P(self.dp_axis))

    def replicated(self):
        return NamedSharding(self.mesh, P())


_state = threading.local()


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build a Mesh over the (global) device list, ICI-contiguous order."""
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError("mesh of %d devices requested, %d available"
                         % (n, len(devices)))
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None,
                       generation: Optional[int] = None) -> MeshSpec:
    """Pure-dp mesh over the current (global) device set.  ``generation``
    defaults to the elastic incarnation counter, so a gang relaunched
    after a resize gets a correctly-stamped mesh for free."""
    devices = jax.devices()
    n = num_devices or len(devices)
    if generation is None:
        try:
            from ..resilience import elastic
            generation = elastic.generation()
        except Exception:
            generation = 0
    return MeshSpec(make_mesh((n,), ("dp",)), generation=generation)


def reform_mesh(spec: MeshSpec, generation: Optional[int] = None,
                devices=None) -> MeshSpec:
    """Re-form ``spec`` over the CURRENT device set — the elastic-resize
    re-layout: after survivors relaunch at a smaller (or restored) world
    size, the same axis layout is rebuilt over however many devices now
    exist, with the generation bumped.  Non-dp axes keep their extent
    (model parallelism doesn't shrink with the fleet); the dp axis
    absorbs the change, so the checkpoint's resharding restore and the
    trainer's grad-accum adjustment see a consistent topology.

    ``devices`` overrides the device set — the warm-standby
    pre-compiler (compile/standby.py) re-forms over a *subset* of the
    live devices to build the N−1 generation's mesh before anything has
    actually died."""
    devices = list(devices) if devices is not None else jax.devices()
    axes = list(spec.mesh.axis_names)
    sizes = dict(spec.mesh.shape)
    other = 1
    for a in axes:
        if a != spec.dp_axis:
            other *= sizes[a]
    if other <= 0 or len(devices) % other:
        raise ValueError(
            "cannot re-form mesh %s over %d devices: non-dp axes need "
            "%d-device multiples" % (dict(sizes), len(devices), other))
    sizes[spec.dp_axis] = len(devices) // other
    shape = tuple(sizes[a] for a in axes)
    gen = spec.generation + 1 if generation is None else int(generation)
    return MeshSpec(make_mesh(shape, axes), dp_axis=spec.dp_axis,
                    tp_axis=spec.tp_axis, pp_axis=spec.pp_axis,
                    sp_axis=spec.sp_axis, ep_axis=spec.ep_axis,
                    generation=gen)


def current_mesh() -> Optional[MeshSpec]:
    return getattr(_state, "mesh", None)


def set_current_mesh(spec: Optional[MeshSpec]):
    _state.mesh = spec


def shard_batch(x, spec: MeshSpec):
    """Place a host batch onto the mesh, sharded along dp."""
    return jax.device_put(x, spec.batch_sharding())


def replicate(x, spec: MeshSpec):
    return jax.device_put(x, spec.replicated())


def describe_devices() -> dict:
    """Topology snapshot for diagnostics (the watchdog post-mortem):
    process rank/count, per-device platform/id/process, and the current
    mesh layout if one is active.  Never raises — each field degrades to
    an error string, because this runs while the program may be wedged."""
    out = {}
    try:
        out["process_index"] = jax.process_index()
        out["process_count"] = jax.process_count()
    except Exception as e:
        out["process"] = repr(e)
    try:
        out["devices"] = [
            {"id": d.id, "platform": d.platform,
             "process_index": d.process_index, "kind": str(d.device_kind)}
            for d in jax.devices()]
    except Exception as e:
        out["devices"] = repr(e)
    try:
        spec = current_mesh()
        if spec is not None:
            out["mesh"] = {"shape": dict(spec.mesh.shape),
                           "axes": list(spec.mesh.axis_names),
                           "generation": spec.generation}
    except Exception as e:
        out["mesh"] = repr(e)
    return out
