"""Device-mesh construction and sharding helpers.

The scaling-book recipe: pick a mesh (axes named for the parallelism kind),
annotate shardings on program inputs/outputs, let XLA insert the
collectives, profile, iterate.  Axis conventions used across mxnet_tpu:

  'dp' — data parallel (batch dim)       → psum(grads) rides ICI
  'tp' — tensor parallel (hidden dims)   → all_gather/reduce_scatter
  'pp' — pipeline stages                 → ppermute
  'sp' — sequence/context parallel       → ring collectives (ring.py)
  'ep' — expert parallel (MoE)           → all_to_all
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["MeshSpec", "make_mesh", "data_parallel_mesh", "reform_mesh",
           "current_mesh", "set_current_mesh", "shard_batch", "replicate",
           "P", "describe_devices"]


class MeshSpec:
    """A mesh plus the axis layout used by the sharded trainer.

    ``generation`` is the elastic-training incarnation counter: every
    coordinated resize (resilience/elastic.py) re-forms the mesh over
    the surviving device set and bumps it, so telemetry digests and the
    fleet view can tell a live row from a pre-resize ghost."""

    def __init__(self, mesh: Mesh, dp_axis="dp", tp_axis=None, pp_axis=None,
                 sp_axis=None, ep_axis=None, generation=0):
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.pp_axis = pp_axis
        self.sp_axis = sp_axis
        self.ep_axis = ep_axis
        self.generation = int(generation)

    @property
    def dp_size(self):
        return self.mesh.shape[self.dp_axis] if self.dp_axis else 1

    def batch_sharding(self):
        return NamedSharding(self.mesh, P(self.dp_axis))

    def replicated(self):
        return NamedSharding(self.mesh, P())


_state = threading.local()


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build a Mesh over the (global) device list, ICI-contiguous order."""
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError("mesh of %d devices requested, %d available"
                         % (n, len(devices)))
    arr = np.array(devices[:n]).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None,
                       generation: Optional[int] = None) -> MeshSpec:
    """Pure-dp mesh over the current (global) device set.  ``generation``
    defaults to the elastic incarnation counter, so a gang relaunched
    after a resize gets a correctly-stamped mesh for free."""
    devices = jax.devices()
    n = num_devices or len(devices)
    if generation is None:
        try:
            from ..resilience import elastic
            generation = elastic.generation()
        except Exception:
            generation = 0
    return MeshSpec(make_mesh((n,), ("dp",)), generation=generation)


def reform_mesh(spec: MeshSpec, generation: Optional[int] = None) -> MeshSpec:
    """Re-form ``spec`` over the CURRENT device set — the elastic-resize
    re-layout: after survivors relaunch at a smaller (or restored) world
    size, the same axis layout is rebuilt over however many devices now
    exist, with the generation bumped.  Non-dp axes keep their extent
    (model parallelism doesn't shrink with the fleet); the dp axis
    absorbs the change, so the checkpoint's resharding restore and the
    trainer's grad-accum adjustment see a consistent topology."""
    devices = jax.devices()
    axes = list(spec.mesh.axis_names)
    sizes = dict(spec.mesh.shape)
    other = 1
    for a in axes:
        if a != spec.dp_axis:
            other *= sizes[a]
    if other <= 0 or len(devices) % other:
        raise ValueError(
            "cannot re-form mesh %s over %d devices: non-dp axes need "
            "%d-device multiples" % (dict(sizes), len(devices), other))
    sizes[spec.dp_axis] = len(devices) // other
    shape = tuple(sizes[a] for a in axes)
    gen = spec.generation + 1 if generation is None else int(generation)
    return MeshSpec(make_mesh(shape, axes), dp_axis=spec.dp_axis,
                    tp_axis=spec.tp_axis, pp_axis=spec.pp_axis,
                    sp_axis=spec.sp_axis, ep_axis=spec.ep_axis,
                    generation=gen)


def current_mesh() -> Optional[MeshSpec]:
    return getattr(_state, "mesh", None)


def set_current_mesh(spec: Optional[MeshSpec]):
    _state.mesh = spec


def shard_batch(x, spec: MeshSpec):
    """Place a host batch onto the mesh, sharded along dp."""
    return jax.device_put(x, spec.batch_sharding())


def replicate(x, spec: MeshSpec):
    return jax.device_put(x, spec.replicated())


def describe_devices() -> dict:
    """Topology snapshot for diagnostics (the watchdog post-mortem):
    process rank/count, per-device platform/id/process, and the current
    mesh layout if one is active.  Never raises — each field degrades to
    an error string, because this runs while the program may be wedged."""
    out = {}
    try:
        out["process_index"] = jax.process_index()
        out["process_count"] = jax.process_count()
    except Exception as e:
        out["process"] = repr(e)
    try:
        out["devices"] = [
            {"id": d.id, "platform": d.platform,
             "process_index": d.process_index, "kind": str(d.device_kind)}
            for d in jax.devices()]
    except Exception as e:
        out["devices"] = repr(e)
    try:
        spec = current_mesh()
        if spec is not None:
            out["mesh"] = {"shape": dict(spec.mesh.shape),
                           "axes": list(spec.mesh.axis_names),
                           "generation": spec.generation}
    except Exception as e:
        out["mesh"] = repr(e)
    return out
