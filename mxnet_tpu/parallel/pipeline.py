"""Pipeline parallelism — micro-batched GPipe schedule over a 'pp' mesh axis.

The reference's model parallelism is sequential layer placement with
_CrossDeviceCopy (graph_executor.cc:313-436, example/model-parallel/lstm) —
device i idles while device j computes.  This module provides the thing the
reference lacks (SURVEY.md §2.3: "No pipelining of micro-batches"): stages
run concurrently on different micro-batches, boundary activations hop one
ring step per tick via lax.ppermute.

Model: `stage_fn(stage_id, params, x) -> y` applied on every device under
shard_map; each device runs its own stage's parameters.  The driver loop
runs M + 2(S - 1) ticks (S stages, M micro-batches), scanning over a
rotating buffer; boundary activations are sent one tick AFTER they are
computed, so every ppermute has a full tick of independent stage compute
to hide behind (collective/compute overlap — the send is off the critical
path).  Backward comes from jax.grad THROUGH the whole schedule — XLA
differentiates the scan+ppermute program, giving 1F1B-equivalent comms.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:   # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:   # 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map

__all__ = ["pipeline_apply", "PipelineRunner"]


def pipeline_apply(stage_fn: Callable, num_stages: int, mesh: Mesh,
                   axis: str, params_stacked, x_micro):
    """Run micro-batches through the stage pipeline.

    stage_fn(params_slice, x) -> y   (same shapes for x and y)
    params_stacked: pytree with leading axis == num_stages (stage i's params)
    x_micro: (M, mb, ...) micro-batched input (global).
    Returns (M, mb, ...) outputs after all stages.

    ``mesh`` may be a Mesh or MeshSpec and may carry other axes (the
    unified dp×tp×pp mesh): the shard_map — retained hand-written
    because a GPipe tick schedule is inherently MPMD-in-time and no
    sharding annotation produces one — is manual only over ``axis`` and
    composes with the GSPMD-managed axes.
    """
    from .placement import as_mesh
    mesh = as_mesh(mesh)
    M = x_micro.shape[0]
    S = num_stages

    def per_device(params_local, x_all):
        # params_local: this device's stage params — shard_map keeps the
        # (sharded) leading stage axis as size 1; squeeze it off
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = jax.lax.axis_index(axis)
        mb_shape = x_all.shape[1:]
        # Overlapped schedule: each boundary activation is SENT one tick
        # after it is computed, so the ppermute's operand comes from the
        # carry and its result is consumed only next tick — the hop has
        # a FULL tick of stage compute that is neither its ancestor nor
        # its descendant to hide behind (the old compute->send->consume
        # tick chained every hop on the critical path: the static
        # overlap instrument read it 0% overlappable).  Stage s runs
        # micro-batch m at tick m + 2s; the fill/drain grows by S-1
        # ticks, amortized at M >> S while EVERY hop is hidden.
        T = M + 2 * (S - 1)

        def tick(carry, t):
            y_send, buf, outputs = carry
            # transfer plane first: forward LAST tick's activation
            # (independent of everything computed this tick)
            perm = [(j, (j + 1) % S) for j in range(S)]
            buf_next = jax.lax.ppermute(y_send, axis, perm)
            # stage 0 ingests micro-batch t (if in range); others take
            # the activation received at the END of the previous tick
            x_in = jnp.where(t < M, x_all[jnp.minimum(t, M - 1)],
                             jnp.zeros(mb_shape, x_all.dtype))
            inp = jnp.where(stage == 0, x_in, buf)
            y = stage_fn(params_local, inp)
            # last stage computes micro-batch (t - 2(S-1)) at tick t
            emit_idx = t - 2 * (S - 1)
            is_emit = (stage == S - 1) & (emit_idx >= 0)
            outputs = jnp.where(
                is_emit,
                outputs.at[jnp.maximum(emit_idx, 0)].set(y),
                outputs)
            return (y, buf_next, outputs), None

        # lax.pvary (varying-axis annotation for check_vma) only exists on
        # jax >= 0.6; on older versions zeros are already unvarying-safe
        pvary = getattr(jax.lax, "pvary", lambda x, axes: x)
        y0 = pvary(jnp.zeros(mb_shape, x_all.dtype), (axis,))
        buf0 = pvary(jnp.zeros(mb_shape, x_all.dtype), (axis,))
        outs0 = pvary(jnp.zeros((M,) + mb_shape, x_all.dtype), (axis,))
        (_, _, outputs), _ = jax.lax.scan(tick, (y0, buf0, outs0),
                                          jnp.arange(T))
        # only the last stage holds real outputs; broadcast them ring-wide
        outputs = jax.lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
            axis)
        return outputs

    in_specs = (P(axis), P())       # params sharded by stage; x replicated
    out_specs = P()
    # pre-pvary jax (< 0.6) cannot prove the scan carry's replication;
    # its own error message prescribes check_rep=False as the workaround
    compat = {} if hasattr(jax.lax, "pvary") else {"check_rep": False}
    mapped = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **compat)
    from .. import telemetry as _tel
    from ..resilience import watchdog as _wd
    from .audit import record_collective
    # boundary activations hop the ring once per tick: (M + 2(S-1))
    # micro-batch-sized ppermutes; the final psum moves the (M, mb)
    # outputs
    act_bytes = int(getattr(x_micro, "nbytes", 0))
    hop_bytes = (act_bytes // max(M, 1)) * (M + 2 * (S - 1))
    with _tel.span("collective/pipeline_apply", cat="collective",
                   metric="parallel.collective_seconds",
                   kind="collective-permute,all-reduce",
                   bytes=hop_bytes + act_bytes), \
            _wd.watch("parallel.pipeline_apply", kind="collective"):
        params_sharded = jax.device_put(
            params_stacked, NamedSharding(mesh, P(axis)))
        x_rep = jax.device_put(x_micro, NamedSharding(mesh, P()))
        out = jax.jit(mapped)(params_sharded, x_rep)
    # the schedule is S+M-1 ppermute ticks PLUS the final psum that
    # broadcasts the last stage's outputs ring-wide — record both kinds
    # or a hang post-mortem would misattribute a stall in the psum
    # (audit-trail gap caught by analysis/graphcheck collective
    # extraction; see tests/test_analysis.py)
    record_collective("collective-permute", "parallel.pipeline_apply",
                      bytes=hop_bytes)
    record_collective("all-reduce", "parallel.pipeline_apply output psum",
                      bytes=act_bytes)
    from ..telemetry import perf as _perf
    _perf.maybe_attribute_fn(mapped, (params_sharded, x_rep),
                             "pipeline_apply", n_devices=S, mesh=mesh)
    return out


class PipelineRunner:
    """Convenience wrapper: homogeneous stages (e.g. stacked transformer
    layers) with stacked parameters, trainable end to end."""

    def __init__(self, stage_fn, num_stages, mesh, axis="pp"):
        from .placement import as_mesh
        self.stage_fn = stage_fn
        self.num_stages = num_stages
        self.mesh = as_mesh(mesh)
        self.axis = axis

    def forward(self, params_stacked, x_micro):
        return pipeline_apply(self.stage_fn, self.num_stages, self.mesh,
                              self.axis, params_stacked, x_micro)

    def loss_and_grad(self, loss_fn, params_stacked, x_micro, y_micro):
        """loss_fn(pred, target) -> scalar; grads w.r.t. stacked params
        differentiate straight through the pipeline schedule."""

        def total_loss(params):
            preds = pipeline_apply(self.stage_fn, self.num_stages, self.mesh,
                                   self.axis, params, x_micro)
            return loss_fn(preds, y_micro)

        return jax.value_and_grad(total_loss)(params_stacked)
