"""Two-tier hierarchical collectives for multi-pod shapes.

One ICI mesh ("island") has ~an order of magnitude more bandwidth than
the DCN/optical tier between islands, so a flat ring all-reduce over a
multi-pod mesh is priced by its slowest links: every byte of the
2(N-1)/N·P per-link ring traffic crosses the slow tier wherever the
ring does.  The two-tier schedule moves only a 1/k weight shard over
the slow tier instead:

1. **in-island reduce-scatter** over the fast axis — each of the k
   in-island ranks ends up owning the island-local sum of ONE 1/k shard;
2. **cross-island exchange** over the slow axis — for each shard,
   exactly one designated rank per island (the in-island rank that owns
   it) all-reduces that P/k shard with its peers in the other m-1
   islands; per designated rank the slow tier carries
   2(m-1)/m · P/k bytes, vs 2(N-1)/N · P on a flat ring's crossing
   link — a ~k× per-link reduction;
3. **in-island all-gather** over the fast axis — every rank reassembles
   the globally-reduced full tensor over fast links.

The audit side lives in parallel/audit.py
(``hierarchical_allreduce_model_bytes``): the compiled program's
per-tier payloads — attributed to mesh axes by the replica-group
labeler — must match this model exactly, which the 2-island×4 dryrun
(tests/test_hierarchy.py) asserts.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:   # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

__all__ = ["two_tier_psum", "hierarchical_allreduce", "flat_allreduce"]

# jax < 0.5 shard_map needs check_rep=False for programs whose
# replication the checker can't prove; jax >= 0.5 dropped the kwarg
_COMPAT = {} if hasattr(jax.lax, "pvary") else {"check_rep": False}


def _count(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def two_tier_psum(v, fast_axis: str, fast_size: int, slow_axis: str):
    """The per-device two-tier all-reduce, for use INSIDE a shard_map
    whose mesh names both axes: reduce-scatter(fast) → psum(slow) on the
    1/k shard → all-gather(fast).  ``v`` is this device's local array;
    returns the global sum with ``v``'s shape.  Arrays whose element
    count does not divide ``fast_size`` are zero-padded for the scatter
    and trimmed after the gather."""
    shape = v.shape
    flat = v.reshape(-1)
    pad = (-flat.size) % max(1, fast_size)
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)])
    shard = jax.lax.psum_scatter(flat, fast_axis, scatter_dimension=0,
                                 tiled=True)
    shard = jax.lax.psum(shard, slow_axis)
    full = jax.lax.all_gather(shard, fast_axis, axis=0, tiled=True)
    if pad:
        full = full[:flat.size - pad]
    return full.reshape(shape)


def hierarchical_allreduce(stacked, mesh, slow_axis: str = "island",
                           fast_axis: str = "dp"):
    """All-reduce per-device values via the two-tier schedule.

    ``stacked`` has shape ``(world, ...)`` — row i is device i's local
    value (island-major device order, matching the mesh) — and the
    result has the same shape with every row equal to the global sum.
    ``mesh`` is a ``jax.sharding.Mesh`` (or MeshSpec) naming both axes.
    """
    mesh = getattr(mesh, "mesh", mesh)
    m = int(mesh.shape[slow_axis])
    k = int(mesh.shape[fast_axis])
    spec = P((slow_axis, fast_axis))

    def per_device(block):          # block: (1, ...) — this device's row
        out = two_tier_psum(block[0], fast_axis, k, slow_axis)
        return out[None]

    mapped = shard_map(per_device, mesh=mesh, in_specs=spec,
                       out_specs=spec, **_COMPAT)
    from ..resilience import watchdog as _wd
    from .audit import hierarchical_allreduce_model_bytes, \
        record_collective
    elem = jnp.dtype(stacked.dtype).itemsize
    payload = _count(stacked.shape) * elem // max(1, m * k)
    model = hierarchical_allreduce_model_bytes(payload, m, k,
                                               elem_bytes=elem)
    with _wd.watch("parallel.hierarchical_allreduce", kind="collective"):
        out = mapped(stacked)
    record_collective("reduce-scatter", "parallel.hierarchical fast tier",
                      bytes=model["reduce-scatter"])
    record_collective("all-reduce", "parallel.hierarchical slow tier",
                      bytes=model["all-reduce"])
    record_collective("all-gather", "parallel.hierarchical fast tier",
                      bytes=model["all-gather"])
    return out


def flat_allreduce(stacked, mesh, slow_axis: str = "island",
                   fast_axis: str = "dp"):
    """The flat (single-ring) baseline over the same stacked layout —
    one psum spanning both tiers; what the hierarchical schedule's
    slow-tier bytes are audited AGAINST."""
    mesh = getattr(mesh, "mesh", mesh)
    spec = P((slow_axis, fast_axis))

    def per_device(block):
        return jax.lax.psum(block[0], (slow_axis, fast_axis))[None]

    mapped = shard_map(per_device, mesh=mesh, in_specs=spec,
                       out_specs=spec, **_COMPAT)
    from ..resilience import watchdog as _wd
    from .audit import record_collective
    world = int(mesh.shape[slow_axis]) * int(mesh.shape[fast_axis])
    with _wd.watch("parallel.flat_allreduce", kind="collective"):
        out = mapped(stacked)
    record_collective(
        "all-reduce", "parallel.flat_allreduce",
        bytes=_count(stacked.shape) * jnp.dtype(stacked.dtype).itemsize
        // max(1, world))
    return out


def hierarchical_grad_allreduce(tree, mesh, slow_axis: str = "island",
                                fast_axis: str = "dp"):
    """Pytree convenience: :func:`hierarchical_allreduce` per leaf."""
    return jax.tree_util.tree_map(
        partial(hierarchical_allreduce, mesh=mesh, slow_axis=slow_axis,
                fast_axis=fast_axis), tree)
