"""Parallelism & distribution over TPU meshes.

This package is the TPU-native replacement for the reference's entire
distribution stack (SURVEY.md §2.3, §5.8): ps-lite/NCCL/CUDA-P2P become XLA
collectives over a jax.sharding.Mesh (ICI intra-slice, DCN across slices).

Modules:
* mesh.py  — ONE named-axis mesh (arbitrary dp/tp/pp/sp/ep layouts via
  MeshSpec.build) + the current-mesh thread-local
* placement.py — the unified placement rules: ``__shard__`` grammar,
  tp recipe, ZeRO state sharding, batch specs (NamedSharding everywhere;
  jit/GSPMD inserts and fuses the collectives)
* trainer.py — sharded train step (dp/tp via GSPMD + the ZeRO sharded
  weight update: reduce-scatter → shard-local update → weight all-gather)
* ring.py / moe.py / pipeline.py — the retained hand-written shard_map
  kernels (ring attention, MoE dispatch, the GPipe tick schedule: the
  programs the partitioner cannot derive), embedded in the same mesh so
  they compose with the GSPMD axes
* audit.py — collective accounting: per-kind AND per-axis payload bytes
  from compiled HLO, with fused all-reduce+slice classified as the
  reduce-scatter it is on the wire
* hierarchy.py — the two-tier (in-island fast / cross-island slow)
  hierarchical all-reduce for multi-pod shapes, audited per tier against
  ``audit.hierarchical_allreduce_model_bytes``
"""
from __future__ import annotations

from collections import namedtuple

import jax
import jax.numpy as jnp
import numpy as np

from .mesh import (MeshSpec, current_mesh, data_parallel_mesh, make_mesh,
                   reform_mesh, set_current_mesh, shard_batch, replicate)

Topology = namedtuple("Topology", ["process_index", "process_count",
                                   "local_device_count",
                                   "global_device_count"])


def topology() -> Topology:
    return Topology(jax.process_index(), jax.process_count(),
                    jax.local_device_count(), jax.device_count())


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Multi-host bootstrap — the tracker/Postoffice analog (reference
    tools/launch.py + ps::Postoffice).  On TPU pods the env provides the
    coordination, so arguments are optional.

    Under tools/launch.py (local multi-process testing, the dmlc-tracker
    local-mode analog) the DMLC_*/MXNET_TPU_* env protocol supplies the
    coordinator and rank, and a cpu backend with gloo collectives is
    configured so DCN logic runs without a pod."""
    import os
    try:  # NOTE: jax.process_count() would itself initialise the backend
        from jax._src import xla_bridge as _xb
        if _xb.backends_are_initialized():
            return  # too late to (re)initialise; runtime already decided
    except Exception:
        pass
    coordinator_address = coordinator_address or \
        os.environ.get("MXNET_TPU_COORDINATOR")
    if num_processes is None and "DMLC_NUM_WORKER" in os.environ:
        num_processes = int(os.environ["DMLC_NUM_WORKER"])
    if process_id is None and "DMLC_WORKER_ID" in os.environ:
        process_id = int(os.environ["DMLC_WORKER_ID"])
    if coordinator_address and (num_processes or 0) > 1:
        if os.environ.get("MXNET_TPU_DIST_DEVICE", "cpu") == "cpu":
            jax.config.update("jax_platforms", "cpu")
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except RuntimeError:
            pass  # repeat call: the service is already up
        return
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except Exception:
        pass  # single-process


def barrier(name="kvstore_barrier"):
    """Global barrier (reference KVStore::Barrier, kvstore.h:349).

    Watchdog-armed: a rank that never arrives leaves the others blocked
    here forever, so the deadline turns that silence into a stack dump +
    post-mortem + fail-fast (resilience/watchdog.py)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        from ..resilience import watchdog as _wd
        from .audit import record_collective
        with _wd.watch("parallel.barrier(%s)" % name, kind="collective"):
            multihost_utils.sync_global_devices(name)
        record_collective("barrier", name)


def allreduce_array(x):
    """Sum an array across processes (DCN allreduce).  Within one process
    the kvstore already reduced device copies; this extends the reduction
    across hosts like the reference's server-side aggregation."""
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils
    from ..resilience import watchdog as _wd
    from .audit import record_collective
    with _wd.watch("parallel.allreduce_array", kind="collective"):
        gathered = multihost_utils.process_allgather(x)
        out = jnp.sum(gathered, axis=0)
    record_collective("all-reduce", "parallel.allreduce_array",
                      bytes=int(getattr(x, "nbytes", 0)))
    return out


def allreduce_row_sparse(rs):
    """Union-sum a RowSparseNDArray across processes without densifying
    (the reference's sparse push aggregation, kvstore_dist_server.h:223).
    nnz differs per rank, so rows are padded to the global max (padding
    ids = -1), allgathered, and merged."""
    if jax.process_count() == 1:
        return rs
    from jax.experimental import multihost_utils
    from ..ndarray.sparse import RowSparseNDArray, merge_row_sparse
    from ..resilience import watchdog as _wd
    from .audit import record_collective
    with _wd.watch("parallel.allreduce_row_sparse", kind="collective"):
        out = _allreduce_row_sparse_impl(rs, multihost_utils,
                                         RowSparseNDArray, merge_row_sparse)
    record_collective("all-gather", "parallel.allreduce_row_sparse")
    return out


def _allreduce_row_sparse_impl(rs, multihost_utils, RowSparseNDArray,
                               merge_row_sparse):
    nnz = rs._data.shape[0]
    max_nnz = int(np.max(multihost_utils.process_allgather(
        jnp.asarray([nnz]))))
    pad = max_nnz - nnz
    data = jnp.pad(rs._data, [(0, pad)] + [(0, 0)] * (rs._data.ndim - 1))
    idx = jnp.pad(rs._indices, (0, pad), constant_values=-1)
    all_data = multihost_utils.process_allgather(data)
    all_idx = np.asarray(multihost_utils.process_allgather(idx))
    parts = []
    for p in range(all_idx.shape[0]):
        keep = all_idx[p] >= 0
        if not np.any(keep):
            continue
        parts.append(RowSparseNDArray(
            jnp.asarray(np.asarray(all_data[p])[keep]),
            jnp.asarray(all_idx[p][keep]), rs.shape))
    return merge_row_sparse(parts) if parts else rs
