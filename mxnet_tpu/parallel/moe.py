"""Expert parallelism (MoE) over the 'ep' mesh axis.

Completes the SURVEY §2.3 parallelism matrix (the reference has no MoE —
this is TPU-native new work, like ring.py/pipeline.py).  Switch-style
top-1 routing in the GShard dispatch/combine-mask formulation: per shard,
routing builds a (tokens, experts, capacity) one-hot dispatch tensor, the
token block is exchanged between devices with ONE lax.all_to_all each way
(riding ICI), each device runs only its local experts, and a combine mask
weighted by the gate probability reassembles the output.  Tokens over an
expert's capacity are dropped (standard switch behavior) and a
load-balancing auxiliary loss keeps routing uniform.

Public API:
  moe_ffn(x, wg, w1, w2, mesh, axis='ep', capacity_factor=1.25,
          activation=relu)
      x: (tokens, d) global, sharded over `axis`; wg: (d, E) replicated;
      w1: (E, d, hidden), w2: (E, hidden, d) sharded over experts.
      Returns (out (tokens, d), aux_loss scalar).
  moe_ffn_dense(...) — single-device exact reference (no capacity drops),
      used by tests and as the n=1 fallback.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
try:   # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:   # 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["moe_ffn", "moe_ffn_dense", "top1_gating"]


def top1_gating(logits, capacity: int):
    """Switch top-1 routing for one token shard.

    logits: (T, E).  Returns (dispatch (T,E,C) 0/1, combine (T,E,C) float,
    aux_loss scalar).  Position-in-expert comes from a cumsum over the
    one-hot assignment; tokens whose position exceeds `capacity` are
    dropped (their dispatch row is all zero, so they pass through as 0 —
    callers usually add a residual connection)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # (T,)
    gate = jnp.max(probs, axis=-1)                           # (T,)
    onehot = jax.nn.one_hot(expert, E, dtype=logits.dtype)   # (T, E)
    # load-balance loss (Switch eq. 4): E * sum_e f_e * P_e
    frac = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    pos = jnp.cumsum(onehot, axis=0) * onehot                # 1-based slots
    keep = (pos > 0) & (pos <= capacity)
    slot = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)
    dispatch = jnp.where(
        keep[..., None],
        jax.nn.one_hot(slot, capacity, dtype=logits.dtype),
        0.0)                                                 # (T, E, C)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine, aux


def _expert_ffn(blocks, w1, w2, activation):
    """blocks: (E_local, C_total, d); w1 (E_local, d, h); w2 (E_local, h, d)."""
    h = jnp.einsum("ecd,edh->ech", blocks, w1)
    h = activation(h)
    return jnp.einsum("ech,ehd->ecd", h, w2)


def _moe_local_fn(axis: str, capacity: int, activation):
    def fn(x, wg, w1, w2):
        # x: (T_local, d) this device's tokens; w1/w2: local expert slices
        logits = x @ wg                                      # (T_l, E)
        dispatch, combine, aux = top1_gating(logits, capacity)
        # pack per-expert token blocks, then ONE all-to-all: expert axis
        # scatters across devices, received blocks stack along capacity
        packed = jnp.einsum("tec,td->ecd", dispatch, x)      # (E, C, d)
        recv = lax.all_to_all(packed, axis, split_axis=0, concat_axis=1,
                              tiled=True)                    # (E_l, n*C, d)
        done = _expert_ffn(recv, w1, w2, activation)
        back = lax.all_to_all(done, axis, split_axis=1, concat_axis=0,
                              tiled=True)                    # (E, C, d)
        out = jnp.einsum("tec,ecd->td", combine, back)
        aux = lax.pmean(aux, axis)
        return out, aux
    return fn


def moe_ffn_dense(x, wg, w1, w2, activation=jax.nn.relu):
    """Exact single-device reference: every token goes through its argmax
    expert, no capacity limit.  O(T*E) compute — test/fallback only."""
    probs = jax.nn.softmax(x @ wg, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    h = activation(jnp.einsum("td,edh->teh", x, w1))
    all_out = jnp.einsum("teh,ehd->ted", h, w2)              # (T, E, d)
    picked = jnp.take_along_axis(
        all_out, expert[:, None, None].repeat(x.shape[-1], -1), 1)[:, 0]
    frac = jax.nn.one_hot(expert, wg.shape[1]).mean(axis=0)
    aux = wg.shape[1] * jnp.sum(frac * probs.mean(axis=0))
    return picked * gate[:, None], aux


def moe_ffn(x, wg, w1, w2, mesh: Mesh, axis: str = "ep",
            capacity_factor: float = 1.25, activation=jax.nn.relu):
    """Sharded gated expert FFN.  x (tokens, d) is sharded over `axis`;
    experts (w1/w2 leading axis) are sharded over `axis`; wg replicated.
    Returns (out, aux_loss); out keeps x's sharding.

    ``mesh`` may be a Mesh or MeshSpec and may carry other axes (the
    unified dp×tp×…×ep mesh): the shard_map — retained hand-written
    because the dispatch/combine all_to_all pair is a schedule the
    partitioner cannot derive from shardings — is manual only over
    ``axis`` and so composes with the GSPMD-managed axes."""
    from .placement import as_mesh
    mesh = as_mesh(mesh)
    n_dev = mesh.shape[axis]
    E = wg.shape[1]
    T = x.shape[0]
    if T % n_dev or E % n_dev:
        raise ValueError("tokens (%d) and experts (%d) must divide the "
                         "'%s' axis size %d" % (T, E, axis, n_dev))
    if n_dev == 1:
        return moe_ffn_dense(x, wg, w1, w2, activation)
    t_local = T // n_dev
    capacity = max(1, math.ceil(t_local * capacity_factor / E))
    fn = _moe_local_fn(axis, capacity, activation)
    # pre-pvary jax (< 0.6) cannot prove the dispatch carry's replication
    compat = {} if hasattr(lax, "pvary") else {"check_rep": False}
    sharded = shard_map(
        fn, mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=(P(axis), P()), **compat)
    from .. import telemetry as _tel
    from ..resilience import watchdog as _wd
    from .audit import record_collective
    # each all_to_all moves the packed (E, C, d) dispatch blocks, f32
    a2a_bytes = 2 * E * capacity * x.shape[-1] * 4
    with _tel.span("collective/moe_ffn", cat="collective",
                   metric="parallel.collective_seconds",
                   kind="all-to-all,all-reduce", bytes=a2a_bytes), \
            _wd.watch("parallel.moe_ffn", kind="collective"):
        out = sharded(x, wg, w1, w2)
    # two all_to_all hops (dispatch + combine) AND the aux-loss pmean —
    # the trail must name every kind in the traced schedule (audit-trail
    # gap caught by analysis/graphcheck collective extraction)
    record_collective("all-to-all", "parallel.moe_ffn dispatch/combine",
                      bytes=a2a_bytes)
    record_collective("all-reduce", "parallel.moe_ffn aux-loss pmean",
                      bytes=4)
    from ..telemetry import perf as _perf
    _perf.maybe_attribute_fn(sharded, (x, wg, w1, w2), "moe_ffn",
                             n_devices=n_dev, mesh=mesh)
    return out
