"""ShardedTrainer — the SPMD training engine.

This is the TPU-native replacement for the reference's entire data-parallel
machinery: DataParallelExecutorGroup's per-device executors + KVStore
reduce/broadcast (executor_group.py:129 + kvstore comm.h) collapse into ONE
jitted step function over a jax Mesh:

  params: replicated over 'dp' (or sharded over 'tp' when a tp axis exists)
  batch:  sharded over 'dp'
  step = forward → loss → grad (XLA inserts psum over dp) → optimizer update

The gradient all-reduce rides ICI as a single fused psum — the kvstore
'device'/'nccl' path taken to its limit.  Donated argnums make the update
in-place in HBM.  Works identically on a CPU device mesh (tests) and a TPU
pod slice (multi-host: same program, jax.distributed handles DCN).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..executor import GraphProgram
from .mesh import MeshSpec

__all__ = ["ShardedTrainer", "sgd_step_fn"]


def _tree_sgd(params, grads, mom, lr, momentum, wd, rescale):
    new_params = []
    new_mom = []
    for p, g, m in zip(params, grads, mom):
        g = g.astype(jnp.float32) * rescale + wd * p
        m2 = momentum * m - lr * g
        new_params.append((p + m2).astype(p.dtype))
        new_mom.append(m2)
    return tuple(new_params), tuple(new_mom)


class ShardedTrainer:
    """One-program data-parallel trainer for a Symbol graph."""

    def __init__(self, symbol, spec: MeshSpec, data_names=("data",),
                 label_names=("softmax_label",), lr=0.01, momentum=0.9,
                 wd=0.0001, loss_scale=1.0, param_dtype=None,
                 shard_optimizer_state=False):
        self.symbol = symbol
        self.spec = spec
        self.prog = GraphProgram(symbol)
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.input_names = self.data_names + self.label_names
        self.param_names = [n for n in self.prog.arg_names
                            if n not in self.input_names]
        self.param_idx = [self.prog.arg_names.index(n)
                          for n in self.param_names]
        self.input_idx = {n: self.prog.arg_names.index(n)
                          for n in self.input_names}
        self.lr = lr
        self.momentum = momentum
        self.wd = wd
        self.param_dtype = param_dtype
        self._step = None
        from ..executor import backward_mirror_policy
        self._built_remat = backward_mirror_policy()
        # tensor parallelism: the tp mesh axis (auto-detected) + per-var
        # __shard__ annotations from the Symbol graph
        tp = spec.tp_axis
        if tp is None and "tp" in spec.mesh.axis_names:
            tp = "tp"
        self.tp_axis = tp if (tp and spec.mesh.shape.get(tp, 1) > 1) else None
        self._shard_attrs = {}
        for node in self.prog.nodes:
            if node.is_var and "__shard__" in node.attrs:
                self._shard_attrs[node.name] = str(node.attrs["__shard__"])
        self._param_shapes = None   # filled by init_state; step shardings
        # ZeRO-style sharded optimizer state (the BIGARRAY/server-side-
        # optimizer analog, kvstore_dist.h:156 + kvstore_dist_server.h:187,
        # SURVEY §5.8): momentum shards over 'dp'; under GSPMD the weight
        # update becomes reduce-scatter grad slice → update owned shard →
        # all-gather new weights (cf. "Automatic Cross-Replica Sharding of
        # Weight Update in Data-Parallel Training").
        self.shard_optimizer_state = bool(shard_optimizer_state)

    # -- tensor-parallel sharding rules -----------------------------------
    def param_sharding(self, name: str, shape) -> NamedSharding:
        """PartitionSpec for one parameter.

        Explicit ``__shard__`` Symbol attr wins (value: comma list of mesh
        axis names / '*' per tensor dim, e.g. ``"tp,*"`` shards dim 0 over
        tp — the ctx_group-style per-layer annotation pattern).  Otherwise,
        when a tp axis is active, the default recipe (SURVEY §2.3: tensor
        parallelism via GSPMD sharding annotations) shards the output
        channels of FC/Convolution weights and the vocab dim of embeddings;
        XLA propagates activation shardings and inserts the collectives.
        """
        mesh = self.spec.mesh
        if self.tp_axis is None:
            return self.spec.replicated()
        tp = self.tp_axis
        size = mesh.shape[tp]
        ann = self._shard_attrs.get(name)
        if ann is not None:
            dims = [None if d.strip() in ("*", "None", "") else d.strip()
                    for d in ann.split(",")]
            if len(dims) > len(shape):
                raise ValueError(
                    "__shard__=%r on %s names %d dims but the tensor has "
                    "%d" % (ann, name, len(dims), len(shape)))
            unknown = [d for d in dims
                       if d is not None and d not in mesh.axis_names]
            if unknown:
                raise ValueError(
                    "__shard__=%r on %s names mesh axes %s not in mesh %s"
                    % (ann, name, unknown, tuple(mesh.axis_names)))
            dims += [None] * (len(shape) - len(dims))
            dims = [d if (d is not None and shape[i] % mesh.shape[d] == 0)
                    else None for i, d in enumerate(dims)]
            return NamedSharding(mesh, P(*dims))
        if name.endswith("_weight") and len(shape) in (2, 4) \
                and shape[0] % size == 0 and shape[0] >= size:
            # FC (out, in) / Conv (out, in, kh, kw) / Embedding (vocab, dim):
            # shard dim 0 (output channels / vocab rows) over tp
            return NamedSharding(mesh, P(*([tp] + [None] * (len(shape) - 1))))
        return self.spec.replicated()

    def mom_sharding(self, name: str, shape) -> NamedSharding:
        """Sharding for one optimizer-state tensor: the param's sharding,
        plus — with shard_optimizer_state — the first free divisible dim
        sharded over 'dp' so per-chip state memory scales down with the
        data-parallel degree."""
        base = self.param_sharding(name, shape)
        if not self.shard_optimizer_state:
            return base
        mesh = self.spec.mesh
        dp = self.spec.dp_axis
        size = mesh.shape.get(dp, 1)
        if size <= 1:
            return base
        dims = list(base.spec) + [None] * (len(shape) - len(base.spec))
        for i, d in enumerate(shape):
            if dims[i] is None and d % size == 0 and d >= size:
                dims[i] = dp
                break
        return NamedSharding(mesh, P(*dims))

    def _param_shardings(self):
        if self._param_shapes is None:
            from ..executor import _resolve_structs
            _, known, _ = _resolve_structs(
                self.symbol, getattr(self, "_last_shapes", {}) or {})
            self._param_shapes = {n: tuple(known[n].shape)
                                  for n in self.param_names if n in known}
        return tuple(self.param_sharding(n, self._param_shapes.get(n, ()))
                     for n in self.param_names)

    def _mom_shardings(self):
        self._param_shardings()   # ensure shapes resolved
        return tuple(self.mom_sharding(n, self._param_shapes.get(n, ()))
                     for n in self.param_names)

    # -- state ------------------------------------------------------------
    def init_state(self, shapes: Dict[str, tuple], initializer=None,
                   seed=0):
        """Initialise (params, mom, aux) replicated on the mesh."""
        from ..executor import _resolve_structs
        from ..initializer import Xavier, InitDesc
        from ..ndarray.ndarray import NDArray
        import numpy as _np
        prog, known, _ = _resolve_structs(self.symbol, shapes)
        self._last_shapes = dict(shapes)
        self._param_shapes = {n: tuple(known[n].shape)
                              for n in self.param_names if n in known}
        initializer = initializer or Xavier(rnd_type="gaussian",
                                            factor_type="in", magnitude=2)
        rep = self.spec.replicated()
        params = []
        # deterministic init independent of global RNG history
        from .. import rng as _rng_mod
        saved = (_rng_mod._get().key, _rng_mod._get().counter)
        _rng_mod.seed(seed)
        for n in self.param_names:
            s = known[n]
            host = _np.zeros(s.shape, _np.float32)
            arr = NDArray(jnp.asarray(host))
            try:
                initializer(InitDesc(n), arr)
                host = arr.asnumpy()
            except Exception:
                pass
            if self.param_dtype is not None and not n.endswith(
                    ("gamma", "beta")):  # BN affine stays fp32
                from ..base import dtype_np
                dt = dtype_np(self.param_dtype)
            else:
                dt = s.dtype
            params.append(jax.device_put(
                host.astype(dt), self.param_sharding(n, s.shape)))
        _rng_mod._get().key, _rng_mod._get().counter = saved
        mom = tuple(jax.device_put(np.zeros(known[n].shape, np.float32),
                                   self.mom_sharding(n, known[n].shape))
                    for n in self.param_names)
        aux = tuple(jax.device_put(
            (np.zeros if "mean" in n else np.ones)(known[n].shape, np.float32),
            rep) for n in self.prog.aux_names)
        return tuple(params), mom, aux

    # -- the step ---------------------------------------------------------
    def _make_step_fn(self):
        """The raw (un-jitted) fused fwd+bwd+SGD step."""
        prog = self.prog
        param_idx = list(self.param_idx)
        input_idx = dict(self.input_idx)
        lr, momentum, wd = self.lr, self.momentum, self.wd

        def loss_fn(params, inputs, aux, keys):
            args = [None] * len(prog.arg_names)
            for i, p in zip(param_idx, params):
                args[i] = p
            for n, v in inputs.items():
                args[input_idx[n]] = v
            outs, new_aux = prog.evaluate(args, aux, keys, True)
            # SoftmaxOutput-style heads carry their gradient via custom vjp;
            # summing outputs triggers it exactly like executor backward
            loss = sum(jnp.sum(o.astype(jnp.float32)) for o in outs)
            return loss, (outs, new_aux)

        from ..executor import _remat_wrap
        loss_fn = _remat_wrap(loss_fn, self._built_remat)

        def step_fn(params, mom, aux, inputs, keys):
            (loss, (outs, new_aux)), grads = jax.value_and_grad(
                loss_fn, argnums=0, has_aux=True)(params, inputs, aux, keys)
            new_params, new_mom = _tree_sgd(
                params, grads, mom, lr, momentum, wd, 1.0)
            return new_params, new_mom, new_aux, loss

        return step_fn

    def _state_shardings(self):
        rep = self.spec.replicated()
        return (self._param_shardings(), self._mom_shardings(),
                tuple(rep for _ in self.prog.aux_names))

    def _build_step(self, donate=True):
        step_fn = self._make_step_fn()
        rep = self.spec.replicated()
        bat = self.spec.batch_sharding()
        pshard, mshard, ashard = self._state_shardings()
        in_shardings = (
            pshard,                                 # params (tp-aware)
            mshard,                                 # mom (ZeRO: +dp-sharded)
            ashard,                                 # aux
            {n: bat for n in self.input_names},     # batch
            rep,                                    # keys
        )
        out_shardings = (pshard, mshard, ashard, rep)
        with self.spec.mesh:
            return jax.jit(step_fn, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=(0, 1, 2) if donate else ())

    def build_step_auto_layout(self, params, mom, aux, batch_shapes,
                               input_dtypes=None):
        """Compile the step letting XLA pick the PARAMETER LAYOUTS, then
        re-lay the state once to match; returns
        (compiled_step, params, mom, aux).

        Why: with NCHW/OIHW graphs the default (row-major) parameter
        layout differs from the layout TPU convolutions want, and with
        fixed input layouts + donation XLA inserts a layout-conversion
        copy of EVERY conv weight and its momentum EVERY step (~250
        copies/step on ResNet-50, measured via tools/hlo_diff.py — a
        fixed ~2.5 ms/step tax at any batch size).  AUTO layouts let the
        compiler store each parameter the way its consumers read it, so
        the donated update aliases cleanly.  Batch inputs and rng keys
        keep default layouts (they arrive fresh from the host each
        step)."""
        from jax.experimental.layout import Format, Layout

        step_fn = self._make_step_fn()
        rep = self.spec.replicated()
        bat = self.spec.batch_sharding()
        pshard, mshard, ashard = self._state_shardings()

        def auto(shardings):
            return tuple(Format(Layout.AUTO, s) for s in shardings)

        in_shardings = (auto(pshard), auto(mshard), auto(ashard),
                        {n: bat for n in self.input_names}, rep)
        out_shardings = (auto(pshard), auto(mshard), auto(ashard), rep)

        def sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        # AOT-compiled executables are dtype-exact: callers feeding
        # non-f32 batches (e.g. the uint8 RecordIO path) must say so
        dts = input_dtypes or {}
        inputs = {n: jax.ShapeDtypeStruct(tuple(batch_shapes[n]),
                                          dts.get(n, jnp.float32))
                  for n in self.input_names}
        keys = self._keys()
        with self.spec.mesh:
            jitted = jax.jit(step_fn, in_shardings=in_shardings,
                             out_shardings=out_shardings,
                             donate_argnums=(0, 1, 2))
            compiled = jitted.lower(
                tuple(sds(p) for p in params), tuple(sds(m) for m in mom),
                tuple(sds(a) for a in aux), inputs, sds(keys)).compile()
        p_fmt, m_fmt, a_fmt = compiled.input_formats[0][:3]
        params = tuple(jax.device_put(p, f) for p, f in zip(params, p_fmt))
        mom = tuple(jax.device_put(m, f) for m, f in zip(mom, m_fmt))
        aux = tuple(jax.device_put(a, f) for a, f in zip(aux, a_fmt))
        return compiled, params, mom, aux

    def step(self, params, mom, aux, batch: Dict[str, np.ndarray]):
        """One synchronous data-parallel SGD step.  batch arrays are global
        (host) arrays; they get sharded over dp."""
        from ..executor import backward_mirror_policy
        remat = backward_mirror_policy()
        if self._step is None or remat != self._built_remat:
            self._built_remat = remat
            self._step = self._build_step()
        inputs = {n: jax.device_put(v, self.spec.batch_sharding())
                  for n, v in batch.items()}
        keys = self._keys()
        return self._step(params, mom, aux, inputs, keys)

    def _keys(self):
        from .. import rng as _rng
        rep = self.spec.replicated()
        if self.prog.num_rng == 0:
            return jax.device_put(jnp.zeros((0, 2), jnp.uint32), rep)
        return jax.device_put(
            jnp.stack([_rng.next_key() for _ in range(self.prog.num_rng)]),
            rep)


def sgd_step_fn(trainer: ShardedTrainer):
    """Expose the raw jitted step (bench/dryrun path).  Buffers are donated
    — params/mom/aux update in place in HBM; callers must rebind their
    references to the returned state every call."""
    if trainer._step is None:
        trainer._step = trainer._build_step()
    return trainer._step
