"""ShardedTrainer — the SPMD training engine.

This is the TPU-native replacement for the reference's entire data-parallel
machinery: DataParallelExecutorGroup's per-device executors + KVStore
reduce/broadcast (executor_group.py:129 + kvstore comm.h) collapse into ONE
jitted step function over a jax Mesh:

  params: replicated over 'dp' (or sharded over 'tp' when a tp axis exists)
  batch:  sharded over 'dp'
  step = forward → loss → grad (XLA inserts psum over dp) → optimizer update

The gradient all-reduce rides ICI as a single fused psum — the kvstore
'device'/'nccl' path taken to its limit.  Donated argnums make the update
in-place in HBM.  Works identically on a CPU device mesh (tests) and a TPU
pod slice (multi-host: same program, jax.distributed handles DCN).
"""
from __future__ import annotations

import contextlib
import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..executor import GraphProgram
from . import placement as _placement
from .mesh import MeshSpec

__all__ = ["ShardedTrainer", "sgd_step_fn", "zero_enabled"]


def zero_enabled(shard_optimizer_state: bool, zero=None) -> bool:
    """Resolve the ZeRO sharded-weight-update knob.

    Precedence: explicit ``zero=`` ctor arg > ``MXNET_TPU_ZERO`` env
    ("1"/"0") > follow ``shard_optimizer_state`` — if you asked for
    dp-sharded optimizer state you get the sharded update too, because
    it is strictly better (identical numerics, 1/dp update FLOPs, the
    grad all-reduce becomes reduce-scatter + overlapped weight
    all-gather).  ``MXNET_TPU_ZERO=0`` reverts to storage-only sharding
    for A/B runs."""
    if zero is not None:
        return bool(zero)
    import os
    v = os.environ.get("MXNET_TPU_ZERO")
    if v is not None:
        return v.strip().lower() not in ("0", "off", "false", "")
    return bool(shard_optimizer_state)


def _tree_sgd(params, grads, mom, lr, momentum, wd, rescale):
    new_params = []
    new_mom = []
    for p, g, m in zip(params, grads, mom):
        g = g.astype(jnp.float32) * rescale + wd * p
        m2 = momentum * m - lr * g
        new_params.append((p + m2).astype(p.dtype))
        new_mom.append(m2)
    return tuple(new_params), tuple(new_mom)


class ShardedTrainer:
    """One-program data-parallel trainer for a Symbol graph."""

    def __init__(self, symbol, spec: MeshSpec, data_names=("data",),
                 label_names=("softmax_label",), lr=0.01, momentum=0.9,
                 wd=0.0001, loss_scale=1.0, param_dtype=None,
                 shard_optimizer_state=False, dynamic_loss_scale=False,
                 loss_scale_growth_interval=2000, nonfinite_budget=None,
                 guard_nonfinite=True, grad_accum=1, zero=None):
        self.symbol = symbol
        self.spec = spec
        self.prog = GraphProgram(symbol)
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.input_names = self.data_names + self.label_names
        self.param_names = [n for n in self.prog.arg_names
                            if n not in self.input_names]
        self.param_idx = [self.prog.arg_names.index(n)
                          for n in self.param_names]
        self.input_idx = {n: self.prog.arg_names.index(n)
                          for n in self.input_names}
        self.lr = lr
        self.momentum = momentum
        self.wd = wd
        self.param_dtype = param_dtype
        # gradient accumulation: one optimizer update per `grad_accum`
        # micro-batches, all inside ONE jitted program (lax.scan over a
        # leading micro dim).  The elastic-training resize uses this to
        # keep the GLOBAL batch constant when the world size changes:
        # accum = global_batch / (world * micro_batch)
        # (resilience/elastic.py grad_accum_for).
        if int(grad_accum) < 1:
            raise ValueError("grad_accum must be >= 1, got %r" % grad_accum)
        self.grad_accum = int(grad_accum)
        self._step = None
        # AOT executable for the step when the persistent compile cache
        # (mxnet_tpu/compile) is armed: the first step either
        # deserializes a warm entry (result=hit — the elastic-resume
        # path) or compiles and writes through (result=miss); later
        # steps call it directly.  None = cache off, dispatch via jit.
        self._step_exec = None
        from ..executor import backward_mirror_policy
        self._built_remat = backward_mirror_policy()
        # tensor parallelism: the tp mesh axis (auto-detected) + per-var
        # __shard__ annotations from the Symbol graph
        tp = spec.tp_axis
        if tp is None and "tp" in spec.mesh.axis_names:
            tp = "tp"
        self.tp_axis = tp if (tp and spec.mesh.shape.get(tp, 1) > 1) else None
        from ..placement import shard_annotations
        self._shard_attrs, self._act_shard_attrs = shard_annotations(
            self.prog.nodes)
        self._param_shapes = None   # filled by init_state; step shardings
        # ZeRO-style sharded weight update (the BIGARRAY/server-side-
        # optimizer analog, kvstore_dist.h:156 + kvstore_dist_server.h:187,
        # SURVEY §5.8; "Automatic Cross-Replica Sharding of Weight Update
        # in Data-Parallel Training", arXiv 2004.13336): momentum shards
        # over 'dp' AND the update math operates on the shards — grads
        # are constrained to the state shardings, so GSPMD reduces each
        # replica's partial straight into the owned shard (reduce-scatter
        # on the wire; XLA:CPU spells it as an all-reduce whose only
        # consumers are partition-sliced — the form the TPU
        # ReduceScatterCreator pass folds), the optimizer update runs at
        # 1/dp FLOPs/bytes per chip, and the new weights all-gather back
        # to their parameter sharding, schedulable against the other
        # parameters' updates.
        self.zero = zero_enabled(shard_optimizer_state, zero)
        self.shard_optimizer_state = bool(shard_optimizer_state) or self.zero
        self.shard_weight_update = self.zero and spec.dp_size > 1
        # -- resilience (resilience/guards.py): the non-finite detector and
        # the loss-scale automaton live INSIDE the jitted step; the host
        # only tracks the consecutive-bad-step budget and chaos hooks.
        from ..resilience import guards as _guards
        self.init_loss_scale = float(loss_scale)
        self.dynamic_loss_scale = bool(dynamic_loss_scale)
        self.loss_scale_growth_interval = int(loss_scale_growth_interval)
        self.guard_nonfinite = bool(guard_nonfinite)
        self.nonfinite_budget = (_guards.default_budget()
                                 if nonfinite_budget is None
                                 else int(nonfinite_budget))
        self._guard_state = None     # (scale f32, good-streak i32) on device
        self._bad_streak = 0
        self._skipped_steps = 0
        self._step_count = 0
        self._last_ok = True
        # -- pre-flight (analysis/preflight.py): with MXNET_TPU_PREFLIGHT=1
        # the first step statically checks the traced program before any
        # device executes it; runs once per trainer.
        self._step_donated = True
        self._preflight_done = False
        # -- attribution (telemetry/perf.py): with MXNET_TPU_ATTRIBUTION=1
        # one roofline/MFU report per step program, written a few steps in
        # so the telemetry histograms carry real measurements.
        self._attribution_done = False

    # -- placement (parallel/placement.py is the single rule source) ------
    def param_sharding(self, name: str, shape) -> NamedSharding:
        """Placement for one parameter: explicit ``__shard__`` Symbol attr
        wins (any mesh axis; the ctx_group-style per-layer annotation
        pattern), else the default tp recipe, else replicated — see
        :func:`~mxnet_tpu.parallel.placement.param_sharding`."""
        return _placement.param_sharding(name, shape, self.spec.mesh,
                                         tp_axis=self.tp_axis,
                                         ann=self._shard_attrs.get(name))

    def mom_sharding(self, name: str, shape) -> NamedSharding:
        """Sharding for one optimizer-state tensor (and, with the ZeRO
        update, the grad/update view of its parameter): the param's
        sharding plus the dp axis over the largest free divisible dim
        (:func:`~mxnet_tpu.parallel.placement.state_sharding`)."""
        base = self.param_sharding(name, shape)
        if not self.shard_optimizer_state:
            return base
        return _placement.state_sharding(base, shape, self.spec.mesh,
                                         self.spec.dp_axis)

    def _param_shardings(self):
        if self._param_shapes is None:
            from ..executor import _resolve_structs
            _, known, _ = _resolve_structs(
                self.symbol, getattr(self, "_last_shapes", {}) or {})
            self._param_shapes = {n: tuple(known[n].shape)
                                  for n in self.param_names if n in known}
        return tuple(self.param_sharding(n, self._param_shapes.get(n, ()))
                     for n in self.param_names)

    def _mom_shardings(self):
        self._param_shardings()   # ensure shapes resolved
        return tuple(self.mom_sharding(n, self._param_shapes.get(n, ()))
                     for n in self.param_names)

    def _arm_mesh(self):
        """Publish this trainer's mesh as the thread's current mesh:
        activation ``__shard__`` constraints (executor hook) resolve
        against it at trace time, and watchdog post-mortems report it."""
        from .mesh import set_current_mesh
        set_current_mesh(self.spec)

    # -- state ------------------------------------------------------------
    def init_state(self, shapes: Dict[str, tuple], initializer=None,
                   seed=0):
        """Initialise (params, mom, aux) replicated on the mesh."""
        self._arm_mesh()
        from ..executor import _resolve_structs
        from ..initializer import Xavier, InitDesc
        from ..ndarray.ndarray import NDArray
        import numpy as _np
        prog, known, _ = _resolve_structs(self.symbol, shapes)
        self._last_shapes = dict(shapes)
        self._param_shapes = {n: tuple(known[n].shape)
                              for n in self.param_names if n in known}
        initializer = initializer or Xavier(rnd_type="gaussian",
                                            factor_type="in", magnitude=2)
        rep = self.spec.replicated()
        params = []
        # deterministic init independent of global RNG history
        from .. import rng as _rng_mod
        saved = (_rng_mod._get().key, _rng_mod._get().counter)
        _rng_mod.seed(seed)
        for n in self.param_names:
            s = known[n]
            host = _np.zeros(s.shape, _np.float32)
            arr = NDArray(jnp.asarray(host))
            try:
                initializer(InitDesc(n), arr)
                host = arr.asnumpy()
            except Exception:
                pass
            if self.param_dtype is not None and not n.endswith(
                    ("gamma", "beta")):  # BN affine stays fp32
                from ..base import dtype_np
                dt = dtype_np(self.param_dtype)
            else:
                dt = s.dtype
            params.append(jax.device_put(
                host.astype(dt), self.param_sharding(n, s.shape)))
        _rng_mod._get().key, _rng_mod._get().counter = saved
        mom = tuple(jax.device_put(np.zeros(known[n].shape, np.float32),
                                   self.mom_sharding(n, known[n].shape))
                    for n in self.param_names)
        aux = tuple(jax.device_put(
            (np.zeros if "mean" in n else np.ones)(known[n].shape, np.float32),
            rep) for n in self.prog.aux_names)
        # memory plane: bucket the trainer's persistent state so live-HBM
        # accounting and OOM forensics can name it (one bool when off)
        from ..telemetry import memory as _memory
        _memory.tag(params, "params", label="ShardedTrainer")
        _memory.tag(mom, "optimizer", label="ShardedTrainer.mom")
        _memory.tag(aux, "params", label="ShardedTrainer.aux")
        return tuple(params), mom, aux

    # -- the step ---------------------------------------------------------
    def _make_step_fn(self):
        """The raw (un-jitted) fused fwd+bwd+SGD step, with the non-finite
        guard and loss-scale automaton compiled in.

        ``guard`` is ``(scale f32, good-streak i32)``.  The loss is
        multiplied by ``scale`` before the backward and the gradients
        divided back in the update, so under- and overflow in low-precision
        graphs are steerable; the ``isfinite`` verdict reduces over the loss
        and every (already psum-reduced) gradient inside the same program —
        every dp replica computes the identical verdict from the identical
        reduced gradients, so the skip/keep select stays SPMD-consistent
        with no extra collective.  A bad step keeps params/mom/aux
        unchanged and halves the scale; good steps grow it back."""
        from ..resilience import guards as _guards
        prog = self.prog
        param_idx = list(self.param_idx)
        input_idx = dict(self.input_idx)
        lr, momentum, wd = self.lr, self.momentum, self.wd
        dynamic = self.dynamic_loss_scale
        growth_interval = self.loss_scale_growth_interval

        def loss_fn(params, inputs, aux, keys):
            args = [None] * len(prog.arg_names)
            for i, p in zip(param_idx, params):
                args[i] = p
            for n, v in inputs.items():
                args[input_idx[n]] = v
            outs, new_aux = prog.evaluate(args, aux, keys, True)
            # SoftmaxOutput-style heads carry their gradient via custom vjp;
            # summing outputs triggers it exactly like executor backward
            loss = sum(jnp.sum(o.astype(jnp.float32)) for o in outs)
            return loss, (outs, new_aux)

        from ..executor import _remat_wrap
        loss_fn = _remat_wrap(loss_fn, self._built_remat)

        def scaled_loss_fn(params, inputs, aux, keys, scale):
            loss, extra = loss_fn(params, inputs, aux, keys)
            return loss * scale, (loss, extra)

        accum = self.grad_accum
        num_rng = prog.num_rng
        # ZeRO sharded weight update: constraining every gradient to its
        # optimizer-state sharding makes GSPMD reduce each replica's
        # partial straight into the owned dp shard (reduce-scatter on the
        # wire) and run the whole update chain below — momentum, weight
        # decay, the non-finite select — at shard shapes (1/dp FLOPs and
        # bytes per chip); the final constraint back to the parameter
        # sharding is the weight all-gather, one per parameter, each
        # independent of every other parameter's update so the scheduler
        # can overlap it (the PR-9 static instrument classifies them
        # pipelined).  Params with no dp-divisible free dim keep their
        # plain all-reduce — GC305 polices whether those bytes matter.
        zero = self.shard_weight_update
        zspecs = self._mom_shardings() if zero else None
        pspecs = self._param_shardings() if zero else None

        def shard_grads(grads):
            if not zero:
                return grads
            return tuple(_placement.constrain(g, s)
                         for g, s in zip(grads, zspecs))

        def step_fn(params, mom, aux, inputs, keys, guard):
            scale, good = guard
            if accum == 1:
                (_, (loss, (outs, new_aux))), grads = jax.value_and_grad(
                    scaled_loss_fn, argnums=0, has_aux=True)(
                        params, inputs, aux, keys, scale)
                grads = shard_grads(grads)
            else:
                # gradient accumulation: inputs carry a leading micro
                # dim (accum, micro_bs, ...); scan folds the micro
                # grads into one f32 accumulator (the memory point of
                # accumulation — one micro-batch of activations live at
                # a time) and aux (BN stats) threads through micros
                # exactly like consecutive steps would.  Loss heads
                # carry per-sample gradients (normalization='null'), so
                # the summed grads equal one big (accum*micro)-batch
                # step bit-for-bit up to fp reassociation.
                def micro_step(carry, micro_inputs):
                    grads_c, aux_c, loss_c, i = carry
                    keys_i = (jax.vmap(
                        lambda k: jax.random.fold_in(k, i))(keys)
                        if num_rng else keys)
                    (_, (loss_i, (_outs, aux_n))), g = jax.value_and_grad(
                        scaled_loss_fn, argnums=0, has_aux=True)(
                            params, micro_inputs, aux_c, keys_i, scale)
                    # with ZeRO each micro's partial reduces straight
                    # into the dp shard, so the f32 accumulator itself
                    # lives sharded (1/dp accumulator HBM) and only ONE
                    # weight all-gather pays for all `accum` reductions
                    grads_c = tuple(gc + gi.astype(jnp.float32)
                                    for gc, gi in zip(grads_c,
                                                      shard_grads(g)))
                    return (grads_c, aux_n, loss_c + loss_i, i + 1), None
                init = (shard_grads(tuple(jnp.zeros(p.shape, jnp.float32)
                                          for p in params)),
                        aux, jnp.float32(0.0), jnp.int32(0))
                (grads, new_aux, loss, _), _ = jax.lax.scan(
                    micro_step, init, inputs)
            new_params, new_mom = _tree_sgd(
                params, grads, mom, lr, momentum, wd, 1.0 / scale)
            ok = _guards.all_finite(loss, grads)
            new_params = tuple(jnp.where(ok, np_, p)
                               for np_, p in zip(new_params, params))
            if zero:
                # the weight all-gather: shard-updated params return to
                # their parameter sharding (replicated over dp)
                new_params = tuple(_placement.constrain(np_, s)
                                   for np_, s in zip(new_params, pspecs))
            new_mom = tuple(jnp.where(ok, nm, m)
                            for nm, m in zip(new_mom, mom))
            new_aux = tuple(jnp.where(ok, na, a)
                            for na, a in zip(new_aux, aux))
            new_scale, new_good = _guards.scale_update(
                scale, good, ok, growth_interval, dynamic=dynamic)
            return (new_params, new_mom, new_aux, loss, ok,
                    (new_scale, new_good))

        return step_fn

    def _state_shardings(self):
        rep = self.spec.replicated()
        return (self._param_shardings(), self._mom_shardings(),
                tuple(rep for _ in self.prog.aux_names))

    def _batch_in_sharding(self):
        """Input sharding for one batch tensor: dp over dim 0, or — with
        grad accumulation — dp over dim 1 under the unsharded micro
        dim the in-jit scan walks."""
        if self.grad_accum > 1:
            return NamedSharding(self.spec.mesh,
                                 P(None, self.spec.dp_axis))
        return self.spec.batch_sharding()

    def _build_step(self, donate=None):
        if donate is None:
            # deserialized executables with donated (aliased) buffers
            # compute wrong results on backends whose runtime never
            # implemented donation (XLA:CPU) — with the compile cache
            # armed there, build donation-free: identical numerics AND
            # identical cost (the runtime was ignoring the donation
            # anyway), and the executable round-trips the cache safely
            # (compile/cache.py donation_safe).
            from .. import compile as _cc
            donate = not (_cc.enabled() and not _cc.donation_safe())
        self._arm_mesh()
        step_fn = self._make_step_fn()
        rep = self.spec.replicated()
        bat = self._batch_in_sharding()
        pshard, mshard, ashard = self._state_shardings()
        in_shardings = (
            pshard,                                 # params (tp-aware)
            mshard,                                 # mom (ZeRO: +dp-sharded)
            ashard,                                 # aux
            {n: bat for n in self.input_names},     # batch
            rep,                                    # keys
            (rep, rep),                             # guard (scale, streak)
        )
        out_shardings = (pshard, mshard, ashard, rep, rep, (rep, rep))
        self._step_donated = bool(donate)   # preflight GC202 checks this
        with self.spec.mesh:
            return jax.jit(step_fn, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=(0, 1, 2, 5) if donate else ())

    def build_step_auto_layout(self, params, mom, aux, batch_shapes,
                               input_dtypes=None):
        """Compile the step letting XLA pick the PARAMETER LAYOUTS, then
        re-lay the state once to match; returns
        (compiled_step, params, mom, aux).

        Why: with NCHW/OIHW graphs the default (row-major) parameter
        layout differs from the layout TPU convolutions want, and with
        fixed input layouts + donation XLA inserts a layout-conversion
        copy of EVERY conv weight and its momentum EVERY step (~250
        copies/step on ResNet-50, measured via tools/hlo_diff.py — a
        fixed ~2.5 ms/step tax at any batch size).  AUTO layouts let the
        compiler store each parameter the way its consumers read it, so
        the donated update aliases cleanly.  Batch inputs and rng keys
        keep default layouts (they arrive fresh from the host each
        step)."""
        try:
            from jax.experimental.layout import Format, Layout
        except ImportError:     # jax <= 0.4.x: pre-rename names
            from jax.experimental.layout import (
                DeviceLocalLayout as Layout, Layout as Format)

        self._arm_mesh()
        step_fn = self._make_step_fn()
        rep = self.spec.replicated()
        bat = self._batch_in_sharding()
        pshard, mshard, ashard = self._state_shardings()

        def auto(shardings):
            return tuple(Format(Layout.AUTO, s) for s in shardings)

        in_shardings = (auto(pshard), auto(mshard), auto(ashard),
                        {n: bat for n in self.input_names}, rep, (rep, rep))
        out_shardings = (auto(pshard), auto(mshard), auto(ashard), rep, rep,
                         (rep, rep))

        def sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        # AOT-compiled executables are dtype-exact: callers feeding
        # non-f32 batches (e.g. the uint8 RecordIO path) must say so
        dts = input_dtypes or {}
        inputs = {n: jax.ShapeDtypeStruct(tuple(batch_shapes[n]),
                                          dts.get(n, jnp.float32))
                  for n in self.input_names}
        self._maybe_preflight(params, mom, aux, inputs)
        keys = self._keys()
        guard = self._guard_arrays()
        from .. import telemetry as _tel
        from .. import compile as _cc
        # same donation rule as _build_step: donation-free when the
        # cache is armed on a backend that never implemented donation
        donate_argnums = ((0, 1, 2, 5)
                          if not (_cc.enabled() and not _cc.donation_safe())
                          else ())
        with self.spec.mesh:
            jitted = jax.jit(step_fn, in_shardings=in_shardings,
                             out_shardings=out_shardings,
                             donate_argnums=donate_argnums)
            with _tel.span("compile/auto_layout", cat="compile",
                           metric="compile.seconds", timed=True) as _cs:
                lowered = jitted.lower(
                    tuple(sds(p) for p in params),
                    tuple(sds(m) for m in mom),
                    tuple(sds(a) for a in aux), inputs, sds(keys),
                    (sds(guard[0]), sds(guard[1])))
                compiled, cc_result = _cc.cached_compile(
                    lowered, "auto_layout", mesh=self.spec.mesh)
                if cc_result == "hit":
                    try:        # the re-lay below needs the layouts; a
                        # deserialized executable that cannot expose
                        # them degrades to a fresh compile
                        _ = (getattr(compiled, "input_formats", None)
                             or compiled.input_layouts)
                    except Exception:
                        compiled, cc_result = lowered.compile(), "miss"
                _cs.attrs["result"] = cc_result
        _tel.tracing.note_compile("train_step_auto_layout", _cs.duration,
                                  symbol=self.symbol.name or "symbol",
                                  result=cc_result)
        from ..telemetry import perf as _perf
        _perf.maybe_attribute(
            compiled,
            "ShardedTrainer.auto_layout(%s)" % (self.symbol.name
                                                or "symbol"),
            n_devices=self.spec.mesh.size, ring_n=self.spec.dp_size,
            mesh=self.spec.mesh)
        fmts = getattr(compiled, "input_formats",
                       None) or compiled.input_layouts
        p_fmt, m_fmt, a_fmt = fmts[0][:3]
        params = tuple(jax.device_put(p, f) for p, f in zip(params, p_fmt))
        mom = tuple(jax.device_put(m, f) for m, f in zip(mom, m_fmt))
        aux = tuple(jax.device_put(a, f) for a, f in zip(aux, a_fmt))
        from ..telemetry import memory as _memory
        if _memory.enabled():
            # re-laid state carries fresh buffers; re-tag them and record
            # this program's compiled memory breakdown for OOM forensics
            _memory.tag(params, "params", label="ShardedTrainer")
            _memory.tag(mom, "optimizer", label="ShardedTrainer.mom")
            _memory.tag(aux, "params", label="ShardedTrainer.aux")
            _memory.note_program(
                "ShardedTrainer.auto_layout(%s)" % (self.symbol.name
                                                    or "symbol"), compiled)
        return compiled, params, mom, aux

    def _compile_step_cached(self, params, mom, aux, inputs, keys):
        """First-step compile through the persistent executable cache
        (mxnet_tpu/compile): returns ``(compiled_or_None, result)`` with
        ``result`` in hit/miss/off.  ``None`` means "dispatch through
        the jit as before" — the cache disabled, or any cache-path
        failure (which must degrade to the stock path, never break a
        step)."""
        from .. import compile as _cc
        if not _cc.enabled():
            return None, "off"
        try:
            def sds(x):
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
            structs = jax.tree_util.tree_map(
                sds, (params, mom, aux, inputs, keys,
                      self._guard_arrays()))
            with self.spec.mesh:
                lowered = self._step.lower(*structs)
            compiled, result = _cc.cached_compile(
                lowered, "train_step", mesh=self.spec.mesh)
            return compiled, result
        except Exception:
            import logging
            logging.exception("compile-cache: trainer step path failed; "
                              "dispatching through jit")
            return None, "off"

    def clone(self, spec: Optional[MeshSpec] = None,
              grad_accum: Optional[int] = None) -> "ShardedTrainer":
        """A sibling trainer with the same symbol/hyperparameters over a
        (possibly different) mesh — the standby pre-compiler's shadow:
        its step program IS the program a post-resize trainer of that
        spec would build, so pre-compiling it warms the real thing."""
        return ShardedTrainer(
            self.symbol, spec if spec is not None else self.spec,
            data_names=self.data_names, label_names=self.label_names,
            lr=self.lr, momentum=self.momentum, wd=self.wd,
            loss_scale=self.init_loss_scale, param_dtype=self.param_dtype,
            shard_optimizer_state=self.shard_optimizer_state,
            dynamic_loss_scale=self.dynamic_loss_scale,
            loss_scale_growth_interval=self.loss_scale_growth_interval,
            nonfinite_budget=self.nonfinite_budget,
            guard_nonfinite=self.guard_nonfinite,
            grad_accum=(grad_accum if grad_accum is not None
                        else self.grad_accum),
            zero=self.zero)

    def lower_step_for(self, devices, grad_accum, state, batch_shapes,
                       input_dtypes=None):
        """Lower the step program as it would exist over ``devices``
        with ``grad_accum`` — the warm-standby entry point
        (compile/standby.py).  ``state`` is this trainer's live
        ``(params, mom, aux)`` (shapes/dtypes are world-independent);
        ``batch_shapes`` are the GLOBAL per-update input shapes.
        Returns ``(lowered, mesh)``; the lowered text is identical to
        what the post-resize trainer's first step will lower, which is
        what makes the cache key match."""
        from .mesh import reform_mesh
        spec = reform_mesh(self.spec, generation=self.spec.generation + 1,
                           devices=devices)
        shadow = self.clone(spec=spec, grad_accum=grad_accum)
        self._param_shardings()          # resolve parent shapes once
        shadow._param_shapes = dict(self._param_shapes or {})
        shadow._last_shapes = dict(getattr(self, "_last_shapes", {}) or {})
        params, mom, aux = state

        def sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        dts = input_dtypes or {}
        accum = shadow.grad_accum
        input_sds = {}
        for n in shadow.input_names:
            shape = tuple(batch_shapes[n])
            if accum > 1:
                if shape[0] % accum:
                    raise ValueError(
                        "global batch dim %d of %r is not divisible by "
                        "grad_accum=%d" % (shape[0], n, accum))
                shape = (accum, shape[0] // accum) + shape[1:]
            input_sds[n] = jax.ShapeDtypeStruct(
                shape, dts.get(n, jnp.float32))
        num_rng = shadow.prog.num_rng
        keys_sds = jax.ShapeDtypeStruct((num_rng if num_rng else 0, 2),
                                        jnp.uint32)
        guard_sds = (jax.ShapeDtypeStruct((), jnp.float32),
                     jax.ShapeDtypeStruct((), jnp.int32))
        jitted = shadow._build_step()
        try:
            with spec.mesh:
                lowered = jitted.lower(
                    tuple(sds(p) for p in params),
                    tuple(sds(m) for m in mom),
                    tuple(sds(a) for a in aux),
                    input_sds, keys_sds, guard_sds)
        finally:
            self._arm_mesh()             # _build_step armed the shadow's
        return lowered, spec.mesh

    def set_grad_accum(self, accum: int):
        """Change the gradient-accumulation factor (one optimizer update
        per ``accum`` micro-batches).  The elastic resize path calls this
        after a world-size change so ``world * micro_batch * accum`` —
        the GLOBAL batch — stays constant.  Rebuilds the step program on
        next use; returns self."""
        accum = int(accum)
        if accum < 1:
            raise ValueError("grad_accum must be >= 1, got %r" % accum)
        if accum != self.grad_accum:
            self.grad_accum = accum
            self._step = None
            self._step_exec = None
        return self

    def _prepare_batch(self, batch):
        """Host-side batch shaping: with grad accumulation the per-update
        batch (accum*micro, ...) folds into (accum, micro, ...) so the
        in-jit scan walks the leading dim."""
        accum = self.grad_accum
        out = {}
        for n, v in batch.items():
            v = np.asarray(v) if not hasattr(v, "reshape") else v
            if accum > 1:
                if v.shape[0] % accum:
                    raise ValueError(
                        "batch dim %d of %r is not divisible by "
                        "grad_accum=%d" % (v.shape[0], n, accum))
                v = v.reshape((accum, v.shape[0] // accum) + v.shape[1:])
            out[n] = v
        return out

    def _put_batch(self, v, local_batch):
        """Device placement for one (already accum-folded) batch tensor.
        ``local_batch``: v is this PROCESS's shard of the global batch
        (multi-host data loading — each rank reads only its part); the
        global array is assembled across processes without any host
        gather."""
        sharding = self._batch_in_sharding()
        if local_batch:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(v))
        return jax.device_put(v, sharding)

    def step(self, params, mom, aux, batch: Dict[str, np.ndarray],
             local_batch: bool = False):
        """One synchronous data-parallel SGD step (one optimizer update =
        ``grad_accum`` micro-batches).  batch arrays are global (host)
        arrays sharded over dp — or, with ``local_batch=True``, each
        process's own shard of the global batch.

        Resilience semantics: a non-finite loss/grad step applies NO
        update (params/mom/aux come back unchanged), backs the loss scale
        off, and — after ``nonfinite_budget`` consecutive bad steps —
        raises :class:`~mxnet_tpu.resilience.guards.NonFiniteError` with
        diagnostics.  Chaos faults (`preempt`, `nan_grad`) are honored
        here so fault drills exercise this exact code path."""
        from .. import telemetry as _tel
        from ..executor import backward_mirror_policy
        from ..resilience import chaos as _chaos
        from ..resilience import watchdog as _watchdog
        from ..telemetry import memory as _memory
        from .audit import record_collective
        self._arm_mesh()
        remat = backward_mirror_policy()
        fresh_program = self._step is None or remat != self._built_remat
        if fresh_program:
            self._built_remat = remat
            self._step = self._build_step()
            self._step_exec = None
        self._step_count += 1
        _chaos.maybe_preempt(self._step_count)
        if _chaos.fire("nan_grad", self._step_count) is not None:
            # poison the batch so the REAL in-step detector trips — the
            # drill proves detection, not a shortcut flag
            poison = self.data_names[0]
            batch = dict(batch)
            batch[poison] = np.full_like(np.asarray(batch[poison]), np.nan)
        batch = self._prepare_batch(batch)
        if not self._preflight_done:
            # trace-check with GLOBAL shapes: under local_batch each
            # process only holds its shard, but the program is SPMD
            mul = jax.process_count() if local_batch else 1
            bdim = 1 if self.grad_accum > 1 else 0
            sds = {}
            for n, v in batch.items():
                shape = list(np.asarray(v).shape)
                shape[bdim] *= mul
                sds[n] = jax.ShapeDtypeStruct(tuple(shape),
                                              np.asarray(v).dtype)
            self._maybe_preflight(params, mom, aux, sds)
        # the deadline covers everything a stall can hide in: the chaos
        # hang drill, host->device transfer, and the jitted step with its
        # fused gradient psum (a dead peer blocks right here); the oom
        # guard turns an allocator RESOURCE_EXHAUSTED anywhere inside
        # into a post-mortem naming the live buffers + this program
        _prog_name = "ShardedTrainer.step(%s)" % (self.symbol.name
                                                  or "symbol")
        with _tel.span("train/step", cat="train",
                       metric="train.step_seconds",
                       step=self._step_count) as _sp, \
                _watchdog.watch("ShardedTrainer.step", kind="step",
                                step=self._step_count), \
                _memory.oom_guard("ShardedTrainer.step",
                                  program=_prog_name,
                                  step=self._step_count):
            _chaos.maybe_hang(self._step_count)
            _chaos.maybe_oom(self._step_count)
            with _tel.span("train/host_enqueue", cat="train",
                           metric="train.host_enqueue_seconds",
                           step=self._step_count):
                inputs = {n: self._put_batch(v, local_batch)
                          for n, v in batch.items()}
                _memory.tag(inputs, "batch", label="ShardedTrainer.step")
                keys = self._keys()
                # compile/ span family (ROADMAP item 5): the first call
                # of a freshly-built jitted step is where trace + lower
                # + compile happen (dispatch is async — the device time
                # lands in train/device_wait, not here), so its duration
                # IS the compile cost; timed=True keeps the ungated
                # compile_seconds ledger extra working when disarmed
                _cspan = (_tel.span("compile/train_step", cat="compile",
                                    metric="compile.seconds", timed=True,
                                    step=self._step_count)
                          if fresh_program else contextlib.nullcontext())
                with _cspan:
                    cc_result = "off"
                    if fresh_program:
                        # persistent compile cache (mxnet_tpu/compile):
                        # when armed, the first step deserializes a warm
                        # executable instead of compiling — the elastic
                        # resume path pays zero compile after a resize
                        self._step_exec, cc_result = \
                            self._compile_step_cached(
                                params, mom, aux, inputs, keys)
                    if self._step_exec is not None:
                        params, mom, aux, loss, ok, guard = \
                            self._step_exec(params, mom, aux, inputs,
                                            keys, self._guard_arrays())
                    else:
                        params, mom, aux, loss, ok, guard = self._step(
                            params, mom, aux, inputs, keys,
                            self._guard_arrays())
                if fresh_program:
                    from ..telemetry import tracing as _tracing
                    _cspan.attrs["result"] = cc_result
                    _tracing.note_compile(
                        "train_step", _cspan.duration,
                        symbol=self.symbol.name or "symbol",
                        result=cc_result)
                self._guard_state = guard
            # host-enqueue vs device-block split: the dispatch above is
            # async; this wait is where device time (and a straggling
            # peer's psum) actually lands.  The explicit sync happens
            # only when spans record — the disarmed hot path keeps the
            # pipelined async dispatch untouched.
            with _tel.span("train/device_wait", cat="train",
                           metric="train.device_wait_seconds",
                           step=self._step_count) as _dw:
                if _dw.active:
                    jax.block_until_ready((loss, ok))
                if self.guard_nonfinite:
                    self._note_step_result(bool(ok), loss)
        _tel.count("train.steps")
        if self.shard_weight_update:
            shardable, residual = self._zero_split_bytes()
            record_collective(
                "reduce-scatter", "ShardedTrainer.step ZeRO grad "
                "reduce-scatter", step=self._step_count, bytes=shardable)
            record_collective(
                "all-gather", "ShardedTrainer.step ZeRO weight all-gather",
                step=self._step_count, bytes=shardable)
            if residual:
                record_collective(
                    "psum", "ShardedTrainer.step residual grad all-reduce "
                    "(no dp-divisible dim)", step=self._step_count,
                    bytes=residual)
        else:
            record_collective("psum",
                              "ShardedTrainer.step dp grad all-reduce",
                              step=self._step_count,
                              bytes=self._grad_bytes())
        _watchdog.heartbeat(self._step_count)
        _tel.window_tick()
        if _memory.enabled():
            # donated updates return fresh buffers each step: keep them
            # bucketed, tick the memory timeline + leak watchdog, and
            # make sure the background sampler runs (armed only)
            _memory.tag(params, "params", label="ShardedTrainer")
            _memory.tag(mom, "optimizer", label="ShardedTrainer.mom")
            _memory.tag(aux, "params", label="ShardedTrainer.aux")
            _memory.note_step(self._step_count)
            _memory.maybe_start_sampler()
        self._maybe_attribute_step(params, mom, aux, inputs, keys)
        return params, mom, aux, loss

    def _maybe_attribute_step(self, params, mom, aux, inputs, keys):
        """Opt-in attribution of the lazily-jitted step program (the
        build_step_auto_layout path attributes its Compiled directly).
        Runs once, a few steps in (MXNET_TPU_ATTRIBUTION_AFTER), so the
        train.step_seconds/host_enqueue/device_wait histograms already
        hold measurements for the report's measured side."""
        from ..telemetry import perf as _perf
        if self._attribution_done or not _perf.enabled():
            return
        if self._step_count < _perf.attribute_after_steps():
            return
        self._attribution_done = True

        def sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        try:
            structs = jax.tree_util.tree_map(
                sds, (params, mom, aux, inputs, keys,
                      self._guard_arrays()))
            compiled = self._step.lower(*structs).compile()
        except Exception:
            import logging
            logging.exception("attribution: step lowering failed "
                              "(continuing)")
            return
        _perf.maybe_attribute(
            compiled,
            "ShardedTrainer.step(%s)" % (self.symbol.name or "symbol"),
            n_devices=self.spec.mesh.size, ring_n=self.spec.dp_size,
            mesh=self.spec.mesh)

    def _zero_split_bytes(self):
        """Split the f32 grad payload into (dp-shardable, residual)
        bytes under the ZeRO update: shardable params reduce-scatter +
        all-gather, the rest (no dp-divisible free dim) keep a plain
        all-reduce.  Feeds the collective telemetry records and the
        audit's analytic model."""
        shapes = self._param_shapes or {}
        dp = self.spec.dp_size
        shardable = residual = 0
        for n in self.param_names:
            shape = shapes.get(n, ())
            nbytes = 4 * int(np.prod(shape)) if shape else 4
            base = self.param_sharding(n, shape)
            dims = list(base.spec) + [None] * (len(shape) - len(base.spec))
            if _placement.zero_shard_dim(shape, dims, dp) is not None:
                shardable += nbytes
            else:
                residual += nbytes
        return shardable, residual

    def _grad_bytes(self):
        """Analytic dp all-reduce payload (f32 grads), cached — feeds the
        collective telemetry record; None before shapes resolve."""
        cached = getattr(self, "_grad_bytes_cache", None)
        if cached is not None:
            return cached
        shapes = self._param_shapes
        if not shapes:
            return None
        total = 0
        for shape in shapes.values():
            n = 1
            for d in shape:
                n *= int(d)
            total += 4 * n
        self._grad_bytes_cache = total
        return total

    def _note_step_result(self, ok, loss):
        """Host half of the guard: budget tracking + graceful abort."""
        self._last_ok = ok
        if ok:
            self._bad_streak = 0
            return
        self._bad_streak += 1
        self._skipped_steps += 1
        from .. import telemetry as _tel
        _tel.count("train.skipped_steps")
        if self._bad_streak > self.nonfinite_budget:
            from ..resilience.guards import NonFiniteError
            raise NonFiniteError(
                "aborting training: %d consecutive non-finite steps "
                "exceeded the budget of %d at step %d (loss=%r, loss "
                "scale now %.4g; %d steps skipped in total).  Restore "
                "the latest checkpoint with a lower lr, or raise "
                "MXNET_TPU_NONFINITE_BUDGET."
                % (self._bad_streak, self.nonfinite_budget,
                   self._step_count, float(loss), self.loss_scale,
                   self._skipped_steps),
                diagnostics={"step": self._step_count,
                             "loss_scale": self.loss_scale,
                             "bad_streak": self._bad_streak,
                             "skipped_steps": self._skipped_steps})

    # -- pre-flight --------------------------------------------------------
    def _maybe_preflight(self, params, mom, aux, batch):
        """Static analysis of the step program before step 0 (opt-in via
        MXNET_TPU_PREFLIGHT=1; analysis/preflight.py).  Trace-only — no
        compile, no device execution — and once per trainer.  Raises
        PreflightError on ERROR-severity findings (action=abort)."""
        if self._preflight_done:
            return
        self._preflight_done = True
        from ..analysis import preflight as _preflight
        if not _preflight.enabled():
            return
        inputs = {n: (v if hasattr(v, "shape") and hasattr(v, "dtype")
                      else np.asarray(v))
                  for n, v in batch.items()}
        inputs = {n: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
                  for n, v in inputs.items()}
        _preflight.run_trainer_preflight(self, params, mom, aux, inputs)

    # -- resilience state --------------------------------------------------
    def _guard_arrays(self):
        """(scale, good-streak) device scalars, created on first use."""
        if self._guard_state is None:
            rep = self.spec.replicated()
            self._guard_state = (
                jax.device_put(jnp.float32(self.init_loss_scale), rep),
                jax.device_put(jnp.int32(0), rep))
        return self._guard_state

    @property
    def loss_scale(self) -> float:
        return float(self._guard_state[0]) if self._guard_state is not None \
            else self.init_loss_scale

    @property
    def skipped_steps(self) -> int:
        return self._skipped_steps

    def resilience_meta(self) -> Dict[str, float]:
        """Guard/progress state a checkpoint must carry to resume
        faithfully (consumed by resilience.checkpoint.save_trainer)."""
        good = int(self._guard_state[1]) if self._guard_state is not None \
            else 0
        return {"loss_scale": self.loss_scale, "good_streak": good,
                "step_count": self._step_count,
                "skipped_steps": self._skipped_steps}

    def set_resilience_state(self, meta):
        """Restore the guard automaton from checkpoint meta."""
        rep = self.spec.replicated()
        self._guard_state = (
            jax.device_put(jnp.float32(meta.get("loss_scale",
                                                self.init_loss_scale)), rep),
            jax.device_put(jnp.int32(meta.get("good_streak", 0)), rep))
        self._step_count = int(meta.get("step_count", 0))
        self._skipped_steps = int(meta.get("skipped_steps", 0))
        self._bad_streak = 0

    def _keys(self):
        from .. import rng as _rng
        rep = self.spec.replicated()
        if self.prog.num_rng == 0:
            return jax.device_put(jnp.zeros((0, 2), jnp.uint32), rep)
        return jax.device_put(
            jnp.stack([_rng.next_key() for _ in range(self.prog.num_rng)]),
            rep)


def sgd_step_fn(trainer: ShardedTrainer):
    """Expose the raw jitted step (bench/dryrun path).  Signature:
    ``step(params, mom, aux, inputs, keys, guard) -> (params, mom, aux,
    loss, ok, guard)`` where ``guard`` comes from
    ``trainer._guard_arrays()``.  Buffers are donated — params/mom/aux/
    guard update in place in HBM; callers must rebind their references to
    the returned state every call."""
    if trainer._step is None:
        trainer._step = trainer._build_step()
    return trainer._step
