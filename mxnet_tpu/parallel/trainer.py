"""ShardedTrainer — the SPMD training engine.

This is the TPU-native replacement for the reference's entire data-parallel
machinery: DataParallelExecutorGroup's per-device executors + KVStore
reduce/broadcast (executor_group.py:129 + kvstore comm.h) collapse into ONE
jitted step function over a jax Mesh:

  params: replicated over 'dp' (or sharded over 'tp' when a tp axis exists)
  batch:  sharded over 'dp'
  step = forward → loss → grad (XLA inserts psum over dp) → optimizer update

The gradient all-reduce rides ICI as a single fused psum — the kvstore
'device'/'nccl' path taken to its limit.  Donated argnums make the update
in-place in HBM.  Works identically on a CPU device mesh (tests) and a TPU
pod slice (multi-host: same program, jax.distributed handles DCN).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..executor import GraphProgram
from .mesh import MeshSpec

__all__ = ["ShardedTrainer", "sgd_step_fn"]


def _tree_sgd(params, grads, mom, lr, momentum, wd, rescale):
    new_params = []
    new_mom = []
    for p, g, m in zip(params, grads, mom):
        g = g.astype(jnp.float32) * rescale + wd * p
        m2 = momentum * m - lr * g
        new_params.append((p + m2).astype(p.dtype))
        new_mom.append(m2)
    return tuple(new_params), tuple(new_mom)


class ShardedTrainer:
    """One-program data-parallel trainer for a Symbol graph."""

    def __init__(self, symbol, spec: MeshSpec, data_names=("data",),
                 label_names=("softmax_label",), lr=0.01, momentum=0.9,
                 wd=0.0001, loss_scale=1.0, param_dtype=None):
        self.symbol = symbol
        self.spec = spec
        self.prog = GraphProgram(symbol)
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.input_names = self.data_names + self.label_names
        self.param_names = [n for n in self.prog.arg_names
                            if n not in self.input_names]
        self.param_idx = [self.prog.arg_names.index(n)
                          for n in self.param_names]
        self.input_idx = {n: self.prog.arg_names.index(n)
                          for n in self.input_names}
        self.lr = lr
        self.momentum = momentum
        self.wd = wd
        self.param_dtype = param_dtype
        self._step = None

    # -- state ------------------------------------------------------------
    def init_state(self, shapes: Dict[str, tuple], initializer=None,
                   seed=0):
        """Initialise (params, mom, aux) replicated on the mesh."""
        from ..executor import _resolve_structs
        from ..initializer import Xavier, InitDesc
        from ..ndarray.ndarray import NDArray
        import numpy as _np
        prog, known, _ = _resolve_structs(self.symbol, shapes)
        initializer = initializer or Xavier(rnd_type="gaussian",
                                            factor_type="in", magnitude=2)
        rep = self.spec.replicated()
        params = []
        # deterministic init independent of global RNG history
        from .. import rng as _rng_mod
        saved = (_rng_mod._get().key, _rng_mod._get().counter)
        _rng_mod.seed(seed)
        for n in self.param_names:
            s = known[n]
            host = _np.zeros(s.shape, _np.float32)
            arr = NDArray(jnp.asarray(host))
            try:
                initializer(InitDesc(n), arr)
                host = arr.asnumpy()
            except Exception:
                pass
            if self.param_dtype is not None and not n.endswith(
                    ("gamma", "beta")):  # BN affine stays fp32
                from ..base import dtype_np
                dt = dtype_np(self.param_dtype)
            else:
                dt = s.dtype
            params.append(jax.device_put(host.astype(dt), rep))
        _rng_mod._get().key, _rng_mod._get().counter = saved
        mom = tuple(jax.device_put(np.zeros(known[n].shape, np.float32), rep)
                    for n in self.param_names)
        aux = tuple(jax.device_put(
            (np.zeros if "mean" in n else np.ones)(known[n].shape, np.float32),
            rep) for n in self.prog.aux_names)
        return tuple(params), mom, aux

    # -- the step ---------------------------------------------------------
    def _build_step(self, donate=True):
        prog = self.prog
        param_idx = list(self.param_idx)
        input_idx = dict(self.input_idx)
        lr, momentum, wd = self.lr, self.momentum, self.wd

        def loss_fn(params, inputs, aux, keys):
            args = [None] * len(prog.arg_names)
            for i, p in zip(param_idx, params):
                args[i] = p
            for n, v in inputs.items():
                args[input_idx[n]] = v
            outs, new_aux = prog.evaluate(args, aux, keys, True)
            # SoftmaxOutput-style heads carry their gradient via custom vjp;
            # summing outputs triggers it exactly like executor backward
            loss = sum(jnp.sum(o.astype(jnp.float32)) for o in outs)
            return loss, (outs, new_aux)

        def step_fn(params, mom, aux, inputs, keys):
            (loss, (outs, new_aux)), grads = jax.value_and_grad(
                loss_fn, argnums=0, has_aux=True)(params, inputs, aux, keys)
            new_params, new_mom = _tree_sgd(
                params, grads, mom, lr, momentum, wd, 1.0)
            return new_params, new_mom, new_aux, loss

        rep = self.spec.replicated()
        bat = self.spec.batch_sharding()
        in_shardings = (
            tuple(rep for _ in self.param_names),   # params
            tuple(rep for _ in self.param_names),   # mom
            tuple(rep for _ in self.prog.aux_names),  # aux
            {n: bat for n in self.input_names},     # batch
            rep,                                    # keys
        )
        out_shardings = (
            tuple(rep for _ in self.param_names),
            tuple(rep for _ in self.param_names),
            tuple(rep for _ in self.prog.aux_names),
            rep,
        )
        with self.spec.mesh:
            return jax.jit(step_fn, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=(0, 1, 2) if donate else ())

    def step(self, params, mom, aux, batch: Dict[str, np.ndarray]):
        """One synchronous data-parallel SGD step.  batch arrays are global
        (host) arrays; they get sharded over dp."""
        if self._step is None:
            self._step = self._build_step()
        inputs = {n: jax.device_put(v, self.spec.batch_sharding())
                  for n, v in batch.items()}
        keys = self._keys()
        return self._step(params, mom, aux, inputs, keys)

    def _keys(self):
        from .. import rng as _rng
        rep = self.spec.replicated()
        if self.prog.num_rng == 0:
            return jax.device_put(jnp.zeros((0, 2), jnp.uint32), rep)
        return jax.device_put(
            jnp.stack([_rng.next_key() for _ in range(self.prog.num_rng)]),
            rep)


def sgd_step_fn(trainer: ShardedTrainer):
    """Expose the raw jitted step (bench/dryrun path).  Buffers are donated
    — params/mom/aux update in place in HBM; callers must rebind their
    references to the returned state every call."""
    if trainer._step is None:
        trainer._step = trainer._build_step()
    return trainer._step
