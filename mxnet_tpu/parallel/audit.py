"""Collective-traffic accounting from compiled HLO.

Gives the scaling story quantitative teeth: parse a compiled step's HLO
for collective instructions, sum their payload bytes, and compare the
data-parallel gradient all-reduce against the analytic ring model
(bytes_on_wire_per_device = 2 * (n-1)/n * payload) that linear-scaling
claims rest on.  Reference anchor: the reference's measured ~90% linear
scaling at 256 GPUs rode exactly this ring-allreduce cost model
(example/image-classification README); on TPU the same math rides ICI.
"""
from __future__ import annotations

import re
import threading
import time
from collections import deque

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

# every collective HLO op we account for
_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
                "collective-permute")

_SHAPE_RE = re.compile(r"(pred|[sufc]\d+|bf16)\[([\d,]*)\]")

# ---------------------------------------------------------------------------
# Runtime collective trail.  The HLO accounting above is static; this is the
# dynamic half: every collective/barrier entry point records a completion
# event here, so when the watchdog (resilience/watchdog.py) fires on a hang
# the post-mortem can say which collective LAST finished — i.e. where in the
# program the ranks diverged.  Bounded deque, thread-safe, ~O(ns) per event.
# ---------------------------------------------------------------------------

_RUNTIME_LOG: "deque" = deque(maxlen=128)
_RUNTIME_LOCK = threading.Lock()


def record_collective(kind: str, tag: str = "", step=None, bytes=None):
    """Note a completed collective (``kind`` = psum/barrier/ppermute/
    all_to_all/..., ``tag`` = call-site label, ``bytes`` = operand
    payload when the entry point knows it).

    Besides the bounded forensic trail, each record fans out into the
    telemetry layer when armed: a ``parallel.collectives`` counter
    (labeled by kind) + ``parallel.collective_bytes``, and a zero-width
    marker in the merged Chrome trace so collective completions line up
    against the span timeline."""
    now = time.time()
    with _RUNTIME_LOCK:
        _RUNTIME_LOG.append({"time": now, "kind": kind,
                             "tag": tag, "step": step, "bytes": bytes})
    from .. import telemetry
    if telemetry.is_armed():
        telemetry.count("parallel.collectives", kind=kind)
        if bytes:
            telemetry.count("parallel.collective_bytes", float(bytes),
                            kind=kind)
    from .. import profiler
    if profiler.is_running():
        args = {"kind": kind, "tag": tag}
        if step is not None:
            args["step"] = step
        if bytes is not None:
            args["bytes"] = int(bytes)
        profiler.record_event("collective/%s" % kind,
                              time.perf_counter() * 1e6, 0.0,
                              cat="collective", args=args)


def last_collective():
    """The most recent completed-collective event, or None."""
    with _RUNTIME_LOCK:
        return dict(_RUNTIME_LOG[-1]) if _RUNTIME_LOG else None


def collective_log(n: int = None):
    """The newest ``n`` (default: all retained) collective events."""
    with _RUNTIME_LOCK:
        items = [dict(e) for e in _RUNTIME_LOG]
    return items[-n:] if n else items


def clear_collective_log():
    with _RUNTIME_LOCK:
        _RUNTIME_LOG.clear()


def _shape_bytes(type_expr):
    """Sum bytes over every dtype[dims] token in an HLO type expression
    (handles tuple-shaped collective outputs)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_expr):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


# ---------------------------------------------------------------------------
# replica-group parsing + mesh-axis attribution
# ---------------------------------------------------------------------------

_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_GROUPS_BRACE_RE = re.compile(
    r"replica_groups=\{(\{[\d, ]*\}(?:, *\{[\d, ]*\})*)\}")
_PAIRS_RE = re.compile(
    r"source_target_pairs=\{(\{[\d, ]*\}(?:, *\{[\d, ]*\})*)\}")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")
_REF_RE = re.compile(r"%([\w.\-]+)")


def parse_replica_groups(attrs_text):
    """``replica_groups`` from an HLO attr string, both syntaxes: the
    explicit brace form ``{{0,4},{1,5}}`` and the iota form
    ``[ngroups,size]<=[dims](T(perm))``.  Returns a list of id tuples or
    None when the instruction carries no groups."""
    m = _GROUPS_IOTA_RE.search(attrs_text)
    if m:
        import numpy as _np
        ngroups, gsize = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        ids = _np.arange(int(_np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(p) for p in m.group(4).split(",")])
        ids = ids.reshape(ngroups, gsize)
        return [tuple(int(x) for x in row) for row in ids]
    m = _GROUPS_BRACE_RE.search(attrs_text)
    if m:
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in re.findall(r"\{([\d, ]*)\}", m.group(1))]
    return None


class AxisLabeler:
    """Attribute a collective's replica groups to the mesh axis (or axis
    combination) they span, so the audit can say which bytes are dp
    traffic vs tp vs ep — the 'per-axis byte accounting' a composed
    dp×tp×pp program needs to be debuggable at all."""

    def __init__(self, mesh):
        self.mesh = getattr(mesh, "mesh", mesh)   # MeshSpec or Mesh
        self._partitions = None

    def _axis_partitions(self):
        """[(label, frozenset-of-frozensets)] for every non-empty subset
        of size>1 axes, smallest subsets first (a dp group must label
        'dp', not 'dp×pp-with-trivial-pp')."""
        if self._partitions is not None:
            return self._partitions
        import itertools
        import numpy as _np
        mesh = self.mesh
        ids = _np.vectorize(lambda d: d.id)(mesh.devices)
        axes = list(mesh.axis_names)
        names = [a for a in axes if mesh.shape[a] > 1]
        parts = []
        for r in range(1, len(names) + 1):
            for sub in itertools.combinations(names, r):
                perm = [i for i, a in enumerate(axes) if a not in sub] + \
                       [i for i, a in enumerate(axes) if a in sub]
                gsize = 1
                for a in sub:
                    gsize *= mesh.shape[a]
                arr = ids.transpose(perm).reshape(-1, gsize)
                key = frozenset(frozenset(int(x) for x in row)
                                for row in arr)
                parts.append(("x".join(sub), key))
        self._partitions = parts
        return parts

    def _all_axes_label(self):
        mesh = self.mesh
        names = [a for a in mesh.axis_names if mesh.shape[a] > 1]
        return "x".join(names) if names else "self"

    def label_groups(self, groups):
        if groups is None:
            return "unmapped"
        key = frozenset(frozenset(g) for g in groups if len(g) > 1)
        if not key:
            return "self"
        for label, part in self._axis_partitions():
            if part == key:
                return label
        return "unmapped"

    def label_pairs(self, pairs):
        """A collective-permute's source_target_pairs belong to the
        smallest axis subset whose device partition keeps every pair
        within one group (the ring axis)."""
        if not pairs:
            return "unmapped"
        for label, part in self._axis_partitions():
            if all(any(s in grp and t in grp for grp in part)
                   for s, t in pairs):
                return label
        return "unmapped"

    def label(self, ins):
        groups = parse_replica_groups(ins.attrs)
        if groups is not None:
            if not groups or all(not g for g in groups):
                # empty groups = every participant
                return self._all_axes_label()
            return self.label_groups(groups)
        m = _PAIRS_RE.search(ins.attrs)
        if m:
            pairs = [tuple(int(x) for x in grp.split(","))
                     for grp in re.findall(r"\{([\d, ]*)\}", m.group(1))
                     if grp.strip()]
            return self.label_pairs([p for p in pairs if len(p) == 2])
        return "unmapped"


def _group_size(ins, default):
    groups = parse_replica_groups(ins.attrs)
    if groups and groups[0]:
        return len(groups[0])
    return default


def _fused_reduce_scatters(instrs_by_comp, num_partitions):
    """The ReduceScatterCreator pattern, detected statically: an
    ``all-reduce`` whose EVERY consumer takes a partition-id-derived
    slice of the result (a ``dynamic-slice`` with partition-dependent
    offsets, or a fusion consuming the full array plus ``partition-id``
    and producing a 1/group shard).  Semantically that pair IS a
    reduce-scatter — the TPU/GPU toolchains' ReduceScatterCreator pass
    rewrites exactly this form into one; XLA:CPU (the dryrun backend)
    lacks the pass and keeps it spelled out, the same way it never emits
    async ``-start``/``-done`` pairs.  Classifying it here keeps the
    audit describing the program's wire semantics rather than one
    backend's pass list — the precedent set by the costmodel's
    'pipelined' overlap classification.

    Returns {(computation, name): shard_payload_bytes}."""
    out = {}
    for comp, instrs in instrs_by_comp.items():
        pids = {i.name for i in instrs if i.opcode == "partition-id"}
        if not pids:
            continue
        refs = {i.name: set(_REF_RE.findall(i.operands)) for i in instrs}
        # scalar offset chains: partition-id flows through multiplies/
        # bitcasts/lookup-table slices into the dynamic-slice offsets
        derived = set(pids)
        changed = True
        while changed:
            changed = False
            for i in instrs:
                if i.name in derived or i.result_bytes > 64:
                    continue
                if refs[i.name] & derived:
                    derived.add(i.name)
                    changed = True
        users = {}
        for i in instrs:
            for r in refs[i.name]:
                users.setdefault(r, []).append(i)
        for i in instrs:
            if i.opcode != "all-reduce":
                continue
            us = users.get(i.name, [])
            if not us:
                continue
            g = _group_size(i, num_partitions)
            if g <= 1:
                continue
            if all(u.opcode in ("dynamic-slice", "fusion")
                   and 2 * u.result_bytes <= i.result_bytes
                   and (refs[u.name] & derived)
                   for u in us):
                out[(comp, i.name)] = i.result_bytes // g
    return out


def collective_accounting(hlo_text, mesh=None):
    """Payload bytes + instruction count per collective kind.

    Returns ``{kind: {"count": int, "bytes": int, ...}}`` over non-fused,
    non-async-duplicate instructions ('-start' variants counted once via
    their operand shapes, '-done' skipped).  Payload conventions: sync
    ops report their result bytes, async ``-start`` their operand bytes,
    reduce-scatter therefore the (1/group) shard.

    Two refinements over raw opcode counting:

    * an all-reduce in the fused all-reduce + partition-slice form (see
      :func:`_fused_reduce_scatters`) is reported as ``reduce-scatter``
      with shard payload, plus a ``fused_from_all_reduce`` count so the
      reclassification is visible;
    * with ``mesh`` given, every kind carries a ``by_axis`` breakdown
      mapping the instruction's replica groups (or ppermute pairs) onto
      the mesh axes — dp vs tp vs ep traffic becomes directly
      attributable in dryrun output.
    """
    from ..analysis.costmodel import iter_instructions
    instrs = list(iter_instructions(hlo_text))
    by_comp = {}
    for ins in instrs:
        by_comp.setdefault(ins.computation, []).append(ins)
    m = _NUM_PARTITIONS_RE.search(hlo_text)
    num_partitions = int(m.group(1)) if m else 1
    fused = _fused_reduce_scatters(by_comp, num_partitions)
    labeler = AxisLabeler(mesh) if mesh is not None else None
    out = {}
    for ins in instrs:
        op = ins.opcode
        is_start = op.endswith("-start")
        base = op[:-len("-start")] if is_start else op
        if base not in _COLLECTIVES or op.endswith("-done"):
            continue
        key = (ins.computation, ins.name)
        if key in fused:
            kind, payload = "reduce-scatter", fused[key]
        elif is_start:
            # async -start result types bundle (operand, result[,
            # scratch]) shapes; the operand shapes from the call args are
            # what the collective is fed (asymmetric all-gather/
            # reduce-scatter fix)
            kind, payload = base, _shape_bytes(ins.operands)
        else:
            kind, payload = base, ins.result_bytes
        slot = out.setdefault(kind, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += payload
        if key in fused:
            slot["fused_from_all_reduce"] = \
                slot.get("fused_from_all_reduce", 0) + 1
        if labeler is not None:
            axis = labeler.label(ins)
            ba = slot.setdefault("by_axis", {}).setdefault(
                axis, {"count": 0, "bytes": 0})
            ba["count"] += 1
            ba["bytes"] += payload
    return out


def ring_allreduce_wire_bytes(payload_bytes, n_devices):
    """Per-device bytes on the wire for a ring all-reduce of ``payload``."""
    return 2 * (n_devices - 1) * payload_bytes // max(1, n_devices)


def collective_wire_bytes(kind, payload_bytes, n_devices):
    """Per-device wire bytes for one collective, per the payload
    conventions of :func:`collective_accounting` (reduce-scatter payload
    is the 1/n output shard; sync all-gather payload is the gathered
    result): ring models in all cases."""
    n = max(1, n_devices)
    if kind == "all-reduce":
        return ring_allreduce_wire_bytes(payload_bytes, n)
    if kind == "reduce-scatter":
        return (n - 1) * payload_bytes
    if kind == "all-gather":
        return (n - 1) * payload_bytes // n
    return payload_bytes


def zero_update_model_bytes(shardable_bytes, residual_bytes, dp):
    """Analytic per-step collective PAYLOADS of the ZeRO sharded weight
    update at dp degree ``dp`` (the audit-side model the dryrun holds
    measurements against): the shardable grads reduce-scatter into 1/dp
    shards, the updated weights all-gather back whole, and params with
    no dp-divisible dim keep a plain all-reduce."""
    return {"reduce-scatter": shardable_bytes // max(1, dp),
            "all-gather": shardable_bytes,
            "all-reduce": residual_bytes}


def hierarchical_allreduce_model_bytes(payload_bytes, islands, per_island,
                                       elem_bytes=4):
    """Analytic per-device collective payloads of the two-tier
    hierarchical all-reduce (parallel/hierarchy.py) of a ``payload``-byte
    tensor on an ``islands`` x ``per_island`` mesh, in the payload
    conventions of :func:`collective_accounting`:

    * ``reduce-scatter`` — in-island (fast tier), payload = the 1/k
      output shard;
    * ``all-reduce`` — cross-island (slow tier), on that 1/k shard;
    * ``all-gather`` — in-island (fast tier), payload = the gathered
      full tensor.

    Plus the two wire numbers the "≪ flat ring" claim is audited with:
    ``slow_wire`` — per-designated-rank bytes crossing the slow tier
    (ring all-reduce of the shard over the m islands) — and
    ``flat_wire`` — what a flat ring over all m*k devices would push
    through its slow-tier crossing links (2(N-1)/N * payload, since a
    flat ring's full per-link traffic rides every link, slow ones
    included)."""
    m = max(1, islands)
    k = max(1, per_island)
    # the scatter pads in ELEMENTS to a multiple of k, so the shard is
    # ceil(elems/k) elements, not ceil(bytes/k) bytes
    elems = -(-payload_bytes // elem_bytes)
    shard = -(-elems // k) * elem_bytes
    return {
        "reduce-scatter": shard,
        "all-reduce": shard,
        "all-gather": shard * k,
        "slow_wire": ring_allreduce_wire_bytes(shard, m),
        "flat_wire": ring_allreduce_wire_bytes(payload_bytes, m * k),
    }


def grad_payload_bytes(params, grad_dtype_bytes=4):
    """Analytic dp all-reduce payload: every gradient, in f32."""
    total = 0
    for p in params:
        n = 1
        for d in p.shape:
            n *= int(d)
        total += n * grad_dtype_bytes
    return total


def audit_report(tag, hlo_text, n_devices, params=None, ring_n=None,
                 mesh=None, zero_model=None, hier_model=None):
    """Format (and return) one accounting line comparing HLO collective
    payloads with the analytic ring model.

    ``ring_n`` is the all-reduce REPLICA-GROUP size (the dp extent) —
    on a dp x tp mesh the gradient ring runs over dp only, not over all
    n_devices.  Pass ``params`` only when the HLO payloads are global
    (pure-dp): with tp the post-SPMD HLO reports per-shard payloads and
    a global-params model would be ~tp x off, so the ratio is skipped.
    ``mesh`` adds the per-axis byte breakdown (dp/tp/sp/ep/pp traffic
    attributed from replica groups).  ``zero_model`` — the dict from
    :func:`zero_update_model_bytes` — swaps the plain grad-payload
    comparison for the ZeRO reduce-scatter + all-gather model.
    ``hier_model`` — from :func:`hierarchical_allreduce_model_bytes` —
    appends the two-tier comparison: per-kind measured/model payloads
    plus the slow-tier wire bytes against the flat-ring baseline.
    """
    ring_n = ring_n or n_devices
    acct = collective_accounting(hlo_text, mesh=mesh)
    parts = []
    for kind in sorted(acct):
        info = acct[kind]
        wire = collective_wire_bytes(kind, info["bytes"], ring_n)
        fused = info.get("fused_from_all_reduce")
        parts.append("%s: %d ops%s, %.2f MB payload, %.2f MB/device on "
                     "wire" % (kind, info["count"],
                               " (%d fused ar+slice)" % fused if fused
                               else "", info["bytes"] / 1e6, wire / 1e6))
    text = "collectives[%s, n=%d, ring=%d] " % (tag, n_devices, ring_n) + \
        ("; ".join(parts) if parts else "none")
    if mesh is not None:
        by_axis = {}
        for kind, info in acct.items():
            for axis, slot in (info.get("by_axis") or {}).items():
                by_axis[axis] = by_axis.get(axis, 0) + slot["bytes"]
        if by_axis:
            text += " | by-axis " + ", ".join(
                "%s: %.2f MB" % (a, b / 1e6)
                for a, b in sorted(by_axis.items()))
    if zero_model is not None:
        model = sum(zero_model.values())
        measured = sum(acct.get(k, {}).get("bytes", 0)
                       for k in zero_model)
        text += (" | analytic ZeRO payload RS %.2f + AG %.2f + AR %.2f MB"
                 " (measured/model = %.2f)"
                 % (zero_model.get("reduce-scatter", 0) / 1e6,
                    zero_model.get("all-gather", 0) / 1e6,
                    zero_model.get("all-reduce", 0) / 1e6,
                    measured / model if model else float("nan")))
    if hier_model is not None:
        kinds = ("reduce-scatter", "all-reduce", "all-gather")
        model = sum(hier_model.get(kd, 0) for kd in kinds)
        measured = sum(acct.get(kd, {}).get("bytes", 0) for kd in kinds)
        slow, flat = hier_model.get("slow_wire", 0), \
            hier_model.get("flat_wire", 0)
        text += (" | analytic 2-tier payload RS %.2f + slowAR %.2f + AG "
                 "%.2f MB (measured/model = %.2f); slow-tier wire %.2f MB"
                 "/rank vs %.2f MB flat ring (%.1fx less)"
                 % (hier_model.get("reduce-scatter", 0) / 1e6,
                    hier_model.get("all-reduce", 0) / 1e6,
                    hier_model.get("all-gather", 0) / 1e6,
                    measured / model if model else float("nan"),
                    slow / 1e6, flat / 1e6,
                    flat / slow if slow else float("nan")))
    elif params is not None:
        model = grad_payload_bytes(params)
        measured = acct.get("all-reduce", {}).get("bytes", 0)
        text += " | analytic grad payload %.2f MB (measured/model = %.2f)" \
            % (model / 1e6, measured / model if model else float("nan"))
    if acct:
        # collective/compute overlap: the standing instrument behind the
        # "collectives overlap compute, spans prove it" perf criterion
        # (telemetry/perf.py folds the same number into attribution
        # reports)
        from ..analysis import costmodel
        ov = costmodel.collective_compute_overlap(hlo_text)
        if ov["overlap_pct"] is not None:
            text += " | collective/compute overlap %.1f%% " \
                "(%d async, %d sync of which %d pipelined)" % (
                    ov["overlap_pct"], ov["async_ops"], ov["sync_ops"],
                    ov.get("pipelined_ops", 0))
    return text, acct
