"""Collective-traffic accounting from compiled HLO.

Gives the scaling story quantitative teeth: parse a compiled step's HLO
for collective instructions, sum their payload bytes, and compare the
data-parallel gradient all-reduce against the analytic ring model
(bytes_on_wire_per_device = 2 * (n-1)/n * payload) that linear-scaling
claims rest on.  Reference anchor: the reference's measured ~90% linear
scaling at 256 GPUs rode exactly this ring-allreduce cost model
(example/image-classification README); on TPU the same math rides ICI.
"""
from __future__ import annotations

import re
import threading
import time
from collections import deque

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

# every collective HLO op we account for
_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
                "collective-permute")

_SHAPE_RE = re.compile(r"(pred|[sufc]\d+|bf16)\[([\d,]*)\]")

# ---------------------------------------------------------------------------
# Runtime collective trail.  The HLO accounting above is static; this is the
# dynamic half: every collective/barrier entry point records a completion
# event here, so when the watchdog (resilience/watchdog.py) fires on a hang
# the post-mortem can say which collective LAST finished — i.e. where in the
# program the ranks diverged.  Bounded deque, thread-safe, ~O(ns) per event.
# ---------------------------------------------------------------------------

_RUNTIME_LOG: "deque" = deque(maxlen=128)
_RUNTIME_LOCK = threading.Lock()


def record_collective(kind: str, tag: str = "", step=None, bytes=None):
    """Note a completed collective (``kind`` = psum/barrier/ppermute/
    all_to_all/..., ``tag`` = call-site label, ``bytes`` = operand
    payload when the entry point knows it).

    Besides the bounded forensic trail, each record fans out into the
    telemetry layer when armed: a ``parallel.collectives`` counter
    (labeled by kind) + ``parallel.collective_bytes``, and a zero-width
    marker in the merged Chrome trace so collective completions line up
    against the span timeline."""
    now = time.time()
    with _RUNTIME_LOCK:
        _RUNTIME_LOG.append({"time": now, "kind": kind,
                             "tag": tag, "step": step, "bytes": bytes})
    from .. import telemetry
    if telemetry.is_armed():
        telemetry.count("parallel.collectives", kind=kind)
        if bytes:
            telemetry.count("parallel.collective_bytes", float(bytes),
                            kind=kind)
    from .. import profiler
    if profiler.is_running():
        args = {"kind": kind, "tag": tag}
        if step is not None:
            args["step"] = step
        if bytes is not None:
            args["bytes"] = int(bytes)
        profiler.record_event("collective/%s" % kind,
                              time.perf_counter() * 1e6, 0.0,
                              cat="collective", args=args)


def last_collective():
    """The most recent completed-collective event, or None."""
    with _RUNTIME_LOCK:
        return dict(_RUNTIME_LOG[-1]) if _RUNTIME_LOG else None


def collective_log(n: int = None):
    """The newest ``n`` (default: all retained) collective events."""
    with _RUNTIME_LOCK:
        items = [dict(e) for e in _RUNTIME_LOG]
    return items[-n:] if n else items


def clear_collective_log():
    with _RUNTIME_LOCK:
        _RUNTIME_LOG.clear()


def _shape_bytes(type_expr):
    """Sum bytes over every dtype[dims] token in an HLO type expression
    (handles tuple-shaped collective outputs)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_expr):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def collective_accounting(hlo_text):
    """Payload bytes + instruction count per collective kind.

    Returns {kind: {"count": int, "bytes": int}} over non-fused,
    non-async-duplicate instructions ('-start' variants counted once,
    '-done' skipped).
    """
    out = {}
    for line in hlo_text.splitlines():
        m = re.match(r"\s*(?:ROOT )?%?[\w.\-]+ = (.+?) ([a-z][\w\-]*)\(",
                     line)
        if not m:
            continue
        type_expr, op = m.groups()
        base = op[:-len("-start")] if op.endswith("-start") else op
        if base not in _COLLECTIVES or op.endswith("-done"):
            continue
        slot = out.setdefault(base, {"count": 0, "bytes": 0})
        slot["count"] += 1
        if op.endswith("-start"):
            # async -start result types bundle (operand, result[, scratch])
            # shapes.  Halving that tuple was only right for symmetric ops
            # (all-reduce); for all-gather/reduce-scatter operand and
            # result differ, so sum the OPERAND shapes from the call args
            # instead — payload is what the collective is fed.
            call = re.search(re.escape(op) + r"\((.*?)\)", line)
            if call:
                payload = _shape_bytes(call.group(1))
            else:   # malformed line: fall back to the symmetric estimate
                payload = _shape_bytes(type_expr) // 2
        else:
            payload = _shape_bytes(type_expr)
        slot["bytes"] += payload
    return out


def ring_allreduce_wire_bytes(payload_bytes, n_devices):
    """Per-device bytes on the wire for a ring all-reduce of ``payload``."""
    return 2 * (n_devices - 1) * payload_bytes // max(1, n_devices)


def grad_payload_bytes(params, grad_dtype_bytes=4):
    """Analytic dp all-reduce payload: every gradient, in f32."""
    total = 0
    for p in params:
        n = 1
        for d in p.shape:
            n *= int(d)
        total += n * grad_dtype_bytes
    return total


def audit_report(tag, hlo_text, n_devices, params=None, ring_n=None):
    """Format (and return) one accounting line comparing HLO collective
    payloads with the analytic ring model.

    ``ring_n`` is the all-reduce REPLICA-GROUP size (the dp extent) —
    on a dp x tp mesh the gradient ring runs over dp only, not over all
    n_devices.  Pass ``params`` only when the HLO payloads are global
    (pure-dp): with tp the post-SPMD HLO reports per-shard payloads and
    a global-params model would be ~tp x off, so the ratio is skipped.
    """
    ring_n = ring_n or n_devices
    acct = collective_accounting(hlo_text)
    parts = []
    for kind in sorted(acct):
        info = acct[kind]
        wire = ring_allreduce_wire_bytes(info["bytes"], ring_n) \
            if kind == "all-reduce" else info["bytes"]
        parts.append("%s: %d ops, %.2f MB payload, %.2f MB/device on wire"
                     % (kind, info["count"], info["bytes"] / 1e6,
                        wire / 1e6))
    text = "collectives[%s, n=%d, ring=%d] " % (tag, n_devices, ring_n) + \
        ("; ".join(parts) if parts else "none")
    if params is not None:
        model = grad_payload_bytes(params)
        measured = acct.get("all-reduce", {}).get("bytes", 0)
        text += " | analytic grad payload %.2f MB (measured/model = %.2f)" \
            % (model / 1e6, measured / model if model else float("nan"))
    if acct:
        # collective/compute overlap: the standing instrument behind the
        # "collectives overlap compute, spans prove it" perf criterion
        # (telemetry/perf.py folds the same number into attribution
        # reports)
        from ..analysis import costmodel
        ov = costmodel.collective_compute_overlap(hlo_text)
        if ov["overlap_pct"] is not None:
            text += " | collective/compute overlap %.1f%% " \
                "(%d async, %d sync of which %d pipelined)" % (
                    ov["overlap_pct"], ov["async_ops"], ov["sync_ops"],
                    ov.get("pipelined_ops", 0))
    return text, acct
